"""Benchmark: flagship-model throughput on the available chip.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

Primary metric: frame-pairs/sec/chip for raft_nc_dbl (NCUP) test-mode
inference at 12 GRU iterations, 368x768 (the Sintel fine-tune crop,
reference: train_raft_nc_sintel.sh:14). Extra fields: ``flops_per_pair``
and ``mfu`` (XLA cost-analysis FLOPs over the chip's peak — see
raft_ncup_tpu/utils/flops.py) and, budget permitting, a train-step
measurement (``train_pairs_per_sec``) plus a PIPELINED train-loop
measurement (``train_loop_pairs_per_sec``: N steps through the async
input pipeline with one end-of-window sync — separates compute from
input/sync stall) since the north-star target is training wall-clock
(BASELINE.json).

Robustness (round-2 postmortem, VERDICT.md "What's weak" #1): the axon TPU
backend can HANG inside ``jax.devices()`` rather than fail fast, and the
driver kills the whole bench at ~900s. So the parent (which never imports
jax) runs everything against one global deadline:

1. A cheap bounded PROBE child (`import jax; jax.devices()`) decides
   whether the inherited backend is alive at all.
2. If alive: ONE full-shape measurement attempt, budgeted to always leave
   the CPU fallback its reserve.
3. Guaranteed CPU fallback at a reduced shape (measured ~85s).

Every path — including total failure — ends with the parent printing one
parseable JSON line and exiting 0. Children print their JSON as soon as
the inference number exists, so even a mid-train-measure kill still
yields a result (harvested from ``TimeoutExpired.stdout``).
"""

from __future__ import annotations

import json
import os
import sys
import time

from raft_ncup_tpu.utils.knobs import (
    knob_enabled,
    knob_flag,
    knob_float,
    knob_int,
    knob_positive_int,
    knob_raw,
    knob_str,
)

_CHILD_ENV = "_RAFT_NCUP_BENCH_CHILD"
_VAL_CHILD_ENV = "_RAFT_NCUP_BENCH_VAL_CHILD"
_REPO = os.path.dirname(os.path.abspath(__file__))
_BASELINE_FILE = os.path.join(_REPO, "docs", "perf_baseline.json")

# Full bench shape (the Sintel fine-tune crop) and the reduced shape used
# for the CPU fallback (full-res NCUP x12 iters on host CPU takes minutes
# per call; the fallback exists to record *a* number, clearly labeled).
FULL = dict(batch=2, height=368, width=768, iters=12)
SMALL = dict(batch=1, height=96, width=128, iters=4)

# Budget arithmetic: the driver's window is ~900s; keep the whole chain
# inside TOTAL_BUDGET_S and always reserve the CPU fallback's slice.
TOTAL_BUDGET_S = knob_float("BENCH_BUDGET_S")
PROBE_TIMEOUT_S = 75.0
TPU_TIMEOUT_CAP_S = 420.0
CPU_RESERVE_S = 280.0


def _baseline_key(platform: str, corr_impl: str, shape: dict) -> str:
    # Host-fingerprinted CPU keys: cross-host CPU numbers differ >2x
    # (VERDICT r2 data). Same fingerprint keys the per-host XLA cache.
    from raft_ncup_tpu.utils.runtime import host_fingerprint

    host = f"@{host_fingerprint()}" if platform == "cpu" else ""
    return (
        f"{platform}{host}:{corr_impl}:{shape['batch']}x{shape['height']}"
        f"x{shape['width']}x{shape['iters']}"
    )


def _load_baselines() -> dict:
    try:
        with open(_BASELINE_FILE) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _emit(record: dict) -> None:
    print(json.dumps(record), flush=True)


def _child_main() -> None:
    """Measure in-process and print result JSON lines (child only).

    Prints the inference record the moment it exists, then (budget
    permitting) re-prints it enriched with the train-step measurement; the
    parent keeps the LAST parseable line.
    """
    t0 = time.monotonic()
    child_budget = float(os.environ.get("_BENCH_CHILD_BUDGET_S", "600"))

    import jax

    from raft_ncup_tpu.utils.runtime import (
        enable_compilation_cache,
        force_platform,
    )

    if "_BENCH_FORCE_PLATFORM" in os.environ:
        force_platform(os.environ["_BENCH_FORCE_PLATFORM"])

    enable_compilation_cache()

    import numpy as np

    from __graft_entry__ import build_forward
    from raft_ncup_tpu.utils.profiling import measure_throughput_detailed

    shape = json.loads(os.environ.get("_BENCH_SHAPE") or json.dumps(FULL))
    corr_impl = knob_str("BENCH_CORR_IMPL")
    nconv_impl = knob_str("RAFT_NCUP_NCONV_IMPL")
    platform = jax.devices()[0].platform
    if (
        platform == "cpu"
        and shape == FULL
        and not knob_flag("BENCH_ALLOW_FULL_ON_CPU")
    ):
        # Full-res NCUP x12 iters is a TPU workload; on a host-CPU backend
        # record the reduced shape rather than time out recording nothing.
        # BENCH_ALLOW_FULL_ON_CPU=1 overrides for the out-of-band anchor
        # row (VERDICT r4 #6): one uncontended full-shape CPU measurement
        # that makes a future TPU number immediately interpretable.
        shape = SMALL
    # The precision policy owns dtype now (docs/PRECISION.md): the primary
    # rows measure the f32 preset on EVERY platform and the `*_bf16` rows
    # carry bf16 — pre-policy this flag was platform != "cpu", which would
    # make the bf16 parity reference itself bf16 on an accelerator and
    # leave the flip gate comparing bf16 against bf16. No TPU baselines
    # were ever pinned (the tunnel has been wedged throughout), so the
    # primary-row semantics change invalidates nothing recorded.
    mixed_precision = False

    if nconv_impl == "pallas":
        # Tally trace-time dispatch so the record can say whether the
        # fused kernel actually ran (ADVICE r3: a row labeled
        # nconv=pallas that silently measured the XLA fallback must not
        # become a pinned baseline).
        from raft_ncup_tpu.ops import nconv as nconv_mod

        nconv_mod.reset_dispatch_counts()
    if corr_impl == "pallas":
        # Same hazard for the corr kernel: zero levels taking the kernel
        # (pltpu missing, or every level over the VMEM budget) means the
        # 'pallas' label would measure pure XLA onthefly.
        from raft_ncup_tpu.ops import corr_pallas as corr_pallas_mod

        corr_pallas_mod.reset_dispatch_counts()

    fwd, (variables, img1, img2) = build_forward(
        shape=(shape["batch"], shape["height"], shape["width"], 3),
        iters=shape["iters"],
        mixed_precision=mixed_precision,
        corr_impl=corr_impl,
    )

    # AOT-compile ONCE and time the compiled executable directly — calling
    # the jitted wrapper after .lower().compile() would compile a second
    # time, and a cold full-shape NCUP compile can take minutes.
    from raft_ncup_tpu.config import flagship_config
    from raft_ncup_tpu.utils import flops as flops_mod

    cfg = flagship_config(
        dataset="sintel", mixed_precision=mixed_precision, corr_impl=corr_impl
    )
    from raft_ncup_tpu.inference import costs as costs_mod

    fwd_flops = None
    flops_source = "analytic"
    forward = None
    cost_entry = None
    try:
        t_compile = time.perf_counter()
        compiled = jax.jit(fwd).lower(variables, img1, img2).compile()
        compile_ms = (time.perf_counter() - t_compile) * 1e3
        forward = compiled
        # The cost ledger (inference/costs.py): the primary row's
        # executable lands in the same process-wide ledger the serving
        # warmups feed, keyed by the bench shape.
        cost_entry = costs_mod.get_cost_ledger().record_compiled(
            f"{platform}|bench_forward|{shape['batch']}x"
            f"{shape['height']}x{shape['width']}x{shape['iters']}"
            f"|{corr_impl}",
            compiled, compile_ms=compile_ms, backend=platform,
            kind="bench_forward",
            shape=(shape["batch"], shape["height"], shape["width"], 3),
            iters=shape["iters"],
        )
        if cost_entry and cost_entry.get("flops"):
            fwd_flops = cost_entry["flops"]
            flops_source = "xla_cost_analysis"
    except Exception as e:  # pragma: no cover - backend-specific
        print(f"AOT compile/cost_analysis unavailable: {e}", file=sys.stderr)
    if forward is None:
        forward = jax.jit(fwd)
    if not fwd_flops:
        fwd_flops = flops_mod.forward_flops(
            cfg, shape["batch"], shape["height"], shape["width"], shape["iters"]
        )

    # On the axon TPU tunnel ``block_until_ready`` returns before the
    # computation finishes; pulling a scalar to host is the only honest
    # synchronization point.
    #
    # --trace_dir / BENCH_TRACE_DIR banks a jax.profiler device trace of
    # the timed reps (utils/profiling.trace): on first hardware contact
    # the same invocation that records the number also records WHERE the
    # time goes (view with TensorBoard's profile plugin / Perfetto).
    from raft_ncup_tpu.utils.profiling import trace

    with trace(knob_raw("BENCH_TRACE_DIR") or None):
        rate, rep_times = measure_throughput_detailed(
            lambda: forward(variables, img1, img2),
            warmup=2,
            reps=5,
            sync=lambda out: np.asarray(out[1][0, 0, 0, 0]),
        )
    pairs_per_sec = shape["batch"] * rate
    flops_per_pair = fwd_flops / shape["batch"]

    # MFU from the per-backend peak table (inference/costs.py): non-null
    # for ANY backend with a known peak entry — CPU included (nominal
    # per-core peak, docs/PERF.md) — null only when the backend itself
    # is unknown. The moment a chip answers, the same line reports real
    # TPU MFU with zero new code (ROADMAP item 1).
    peak = costs_mod.peak_flops(
        platform,
        device_kind=getattr(jax.devices()[0], "device_kind", None),
        tpu_gen=os.environ.get("PALLAS_AXON_TPU_GEN"),
    )
    mfu = costs_mod.mfu(flops_per_pair, pairs_per_sec, peak)

    impl_label = corr_impl + (
        f"+nconv_{nconv_impl}" if nconv_impl != "xla" else ""
    )
    key = _baseline_key(platform, impl_label, shape)
    baseline = _load_baselines().get(key)
    vs = pairs_per_sec / baseline if baseline else 1.0
    record = {
        "metric": (
            f"raft_nc_dbl frame-pairs/sec/chip @ {shape['iters']} "
            f"iters {shape['height']}x{shape['width']} "
            f"({platform}, corr={corr_impl}, nconv={nconv_impl})"
        ),
        "value": round(pairs_per_sec, 4),
        "unit": "pairs/s",
        "vs_baseline": round(vs, 3),
        "baseline_key": key,
        "flops_per_pair": round(flops_per_pair, 0),
        "flops_source": flops_source,
        "mfu": mfu,
        "mfu_peak_flops": peak,
        "mfu_backend": platform,
        # Per-rep wall times: single-shot CPU numbers wobble ±5-10% on a
        # shared host (VERDICT r4 weak #1); the spread makes cross-round
        # deltas interpretable.
        "rep_ms": [round(t * 1e3, 1) for t in rep_times],
        # Budgeted vs executed iterations (docs/PERF.md "Early exit").
        # This row runs the plain full-budget scan — no convergence
        # detection — so executed == budgeted, recorded explicitly so
        # every row answers the same "how much refinement actually ran"
        # question the earlyexit_* row varies.
        "iters_budgeted": shape["iters"],
        "iters_executed_mean": float(shape["iters"]),
        "iters_executed_p50": shape["iters"],
        "iters_executed_p99": shape["iters"],
    }
    if cost_entry is not None:
        # The executable's own cost facts, recorded at compile time
        # (bytes from XLA cost analysis; compiled_memory_stats from
        # memory_analysis) — the ledger row the autotuner will read.
        record["bytes_per_pair"] = (
            None if cost_entry.get("bytes_accessed") is None
            else round(cost_entry["bytes_accessed"] / shape["batch"], 0)
        )
        record["compile_ms"] = cost_entry.get("compile_ms")
        record["compiled_memory_stats"] = cost_entry.get("memory_stats")
    trace_dir = knob_raw("BENCH_TRACE_DIR")
    if trace_dir:
        record["trace_dir"] = trace_dir
    if nconv_impl == "pallas":
        counts = nconv_mod.dispatch_counts()
        # Mirror corr_pallas_levels: partial fusion (some call sites gated
        # out by the VMEM budget) is labeled-but-annotated, not demoted —
        # only ZERO fused calls makes the 'pallas' label a lie (ADVICE r4).
        record["fused_ok"] = bool(counts["fused"] > 0)
        record["nconv_pallas_calls"] = (
            f"{counts['fused']}/{counts['fused'] + counts['fallback']}"
        )
        if not record["fused_ok"]:
            print(
                f"nconv=pallas dispatch counts {counts}: the fused kernel "
                "never ran — this row measures the XLA path",
                file=sys.stderr,
            )
    if corr_impl == "pallas":
        ccounts = corr_pallas_mod.dispatch_counts()
        # Partial per-level fallback is by design; a level on the BANDED
        # tier is still the fused kernel (three-tier dispatch,
        # ops/corr_pallas.py) — only zero kernel-tier levels makes the
        # label a lie.
        on_kernel = ccounts["kernel"] + ccounts["banded"]
        corr_ok = on_kernel > 0
        record["fused_ok"] = bool(record.get("fused_ok", True) and corr_ok)
        record["corr_pallas_levels"] = (
            f"{on_kernel}/{ccounts['levels_total']}"
        )
        record["corr_pallas_banded_levels"] = ccounts["banded"]
        if not corr_ok:
            print(
                f"corr=pallas dispatch counts {ccounts}: no level ran the "
                "kernel — this row measures the XLA onthefly path",
                file=sys.stderr,
            )
    _emit(record)

    # Train-step measurement (north star is training wall-clock) — only if
    # at least ~45% of the child budget remains. BENCH_SKIP_TRAIN=1 turns
    # off BOTH train rows — the isolated step and the pipelined loop —
    # explicitly (the full-shape CPU anchor: a fwd+bwd at 368x768 on a
    # 1-core host would run for tens of minutes).
    remaining = child_budget - (time.monotonic() - t0)
    if knob_flag("BENCH_SKIP_TRAIN"):
        pass
    elif remaining > 0.45 * child_budget:
        handles = None
        try:
            train, handles = _measure_train_step(
                shape, mixed_precision, corr_impl
            )
            record.update(train)
            _emit(record)
        except Exception as e:  # never lose the inference record
            print(f"train-step bench failed: {e}", file=sys.stderr)
        # Pipelined-loop row: N steps through the async input pipeline
        # (device prefetch + device-accumulated metrics, one sync at the
        # end) vs the per-step-synced row above. The delta is the
        # input/sync stall the pipeline does (or does not) hide — see
        # docs/PERF.md for how the stall fraction is derived.
        if (
            handles is not None
            and child_budget - (time.monotonic() - t0) > 0.2 * child_budget
        ):
            try:
                loop = _measure_train_loop(handles)
                if "train_ms_per_step" in record:
                    loop["train_loop_stall_ms_per_step"] = round(
                        loop["train_loop_ms_per_step"]
                        - record["train_ms_per_step"],
                        1,
                    )
                record.update(loop)
                _emit(record)
            except Exception as e:  # never lose the per-step record
                print(f"train-loop bench failed: {e}", file=sys.stderr)
        # Checkpoint save/restore latency row (resilience): after the
        # loop row so it cannot perturb the throughput numbers; a sliver
        # of budget suffices (one save + one restore of the train state).
        if (
            handles is not None
            and child_budget - (time.monotonic() - t0) > 0.08 * child_budget
        ):
            try:
                record.update(_measure_checkpoint(handles))
                _emit(record)
            except Exception as e:  # never lose the earlier rows
                print(f"checkpoint bench failed: {e}", file=sys.stderr)

    # Eval-pipeline row (docs/PERF.md "Eval pipeline"): the pipelined
    # validation loop (decode-ahead + device-resident metrics + one
    # end-of-window sync) vs the per-batch-synced loop on the SAME warm
    # executable. The delta is the decode + sync stall the async eval
    # pipeline recovers per pair. Independent of the train gate (it is
    # an inference-path row); BENCH_SKIP_VAL=1 turns it off explicitly.
    # On CPU the measurement runs in a sub-child whose XLA host pool
    # leaves a core free for the input pipeline (the serving
    # configuration — with the default all-cores pool, "overlap" can
    # only steal compute cores and the comparison measures contention,
    # not pipelining); accelerators leave the host pool free by nature
    # and measure in-process against the inference row's variables.
    if knob_flag("BENCH_SKIP_VAL"):
        pass
    elif child_budget - (time.monotonic() - t0) > 0.12 * child_budget:
        try:
            val = None
            if platform == "cpu":
                spare = child_budget - (time.monotonic() - t0) - 10.0
                val = _run_val_child(shape, corr_impl, min(300.0, spare))
                if val is None:
                    print(
                        "val sub-child yielded nothing; measuring "
                        "in-process (shared XLA pool — expect contention)",
                        file=sys.stderr,
                    )
            if val is None:
                val = _measure_val_loop(
                    shape, mixed_precision, corr_impl, variables
                )
            record.update(val)
            _emit(record)
        except Exception as e:  # never lose the earlier rows
            print(f"val-loop bench failed: {e}", file=sys.stderr)

    # Serving row (docs/SERVING.md; docs/PERF.md "Serving"): steady-state
    # open-loop serving through the FlowServer front-end — admission,
    # budget decisions, host staging, micro-batch forward, AsyncDrain
    # result pull — measured under the runtime guards like the train/val
    # rows. `serve_recompiles`/`serve_host_transfers` must be 0 in steady
    # state (the per-batch result pull is the sanctioned explicit
    # device_get in the drain worker — the product, not a leak).
    # BENCH_SKIP_SERVE=1 turns it off explicitly.
    if knob_flag("BENCH_SKIP_SERVE"):
        pass
    elif child_budget - (time.monotonic() - t0) > 0.08 * child_budget:
        try:
            record.update(_measure_serve(shape, mixed_precision,
                                         corr_impl, variables))
            _emit(record)
        except Exception as e:  # never lose the earlier rows
            print(f"serve bench failed: {e}", file=sys.stderr)

    # Streaming row (docs/STREAMING.md; docs/PERF.md "Streaming"):
    # steady-state multi-stream video through the StreamEngine — slot
    # gather, in-graph warm-start splat, batched forward, anomaly check,
    # scatter, AsyncDrain pull — under the same guards. The warm slot
    # table and fixed per-batch-size executable set are the recompile-
    # free contract: `stream_recompiles`/`stream_host_transfers` must be
    # 0. BENCH_SKIP_STREAM=1 turns it off explicitly.
    if knob_flag("BENCH_SKIP_STREAM"):
        pass
    elif child_budget - (time.monotonic() - t0) > 0.08 * child_budget:
        try:
            record.update(_measure_stream(shape, mixed_precision,
                                          corr_impl, variables))
            _emit(record)
        except Exception as e:  # never lose the earlier rows
            print(f"stream bench failed: {e}", file=sys.stderr)

    # Fleet row (docs/FLEET.md; docs/PERF.md "Fleet"): N real serve.py
    # replica processes behind the host-only FleetRouter, the same
    # open-loop steady-state window as the serve row — fleet_p50/p99 vs
    # serve_p50/p99 is the measured router-hop cost, per-replica guard
    # counters must all be 0, and every replica drains on the exit-75
    # contract at teardown. Spawns processes (each pays its own model
    # warmup), so it rides a generous budget gate;
    # BENCH_SKIP_FLEET=1 turns it off explicitly.
    if knob_flag("BENCH_SKIP_FLEET"):
        pass
    elif child_budget - (time.monotonic() - t0) > 0.3 * child_budget:
        try:
            record.update(_measure_fleet(shape, corr_impl))
            _emit(record)
        except Exception as e:  # never lose the earlier rows
            print(f"fleet bench failed: {e}", file=sys.stderr)

    # Elasticity row (docs/FLEET.md "Elasticity bench"; ROADMAP item 3):
    # the SLO-driven autoscaler replaying the deterministic low→high→
    # cooldown traffic step on a real min=1/max=2 fleet — did the step
    # force a scale-up, how long to READY, did the calm give capacity
    # back with zero in-flight loss, and were warmup-window sheds
    # ETA-floored. The row MEASURES the robustness machinery (sheds are
    # expected; losses/violations disqualify it — the inverse of the
    # fleet row's steady-state discipline). Spawns processes and rides
    # out a spawn compile, hence the generous gate;
    # BENCH_SKIP_ELASTICITY=1 turns it off explicitly.
    if knob_flag("BENCH_SKIP_ELASTICITY"):
        pass
    elif child_budget - (time.monotonic() - t0) > 0.3 * child_budget:
        try:
            record.update(_measure_elasticity(shape, corr_impl))
            _emit(record)
        except Exception as e:  # never lose the earlier rows
            print(f"elasticity bench failed: {e}", file=sys.stderr)

    # bf16 rows (docs/PRECISION.md; ROADMAP item 3): the same guarded
    # forward / train-loop / val / serve / stream measurements re-run
    # under the precision policy's bf16 presets, every key suffixed
    # `_bf16`. The forward row additionally records the parity field
    # (`bf16_forward_epe_vs_f32`, vs the f32 executable on the same
    # inputs) and the test-pinned budget, so flip_recommendations can
    # gate a default flip on MEASURED parity + clean guard counters —
    # the corr_impl discipline applied to precision. The same f32
    # variables serve both (f32 master weights; modules cast).
    # BENCH_SKIP_BF16=1 turns the whole block off explicitly. On CPU
    # bf16 is emulated (slower, parity still meaningful); the rows are
    # first in line for real numbers when a chip answers.
    if knob_flag("BENCH_SKIP_BF16"):
        pass
    elif child_budget - (time.monotonic() - t0) > 0.3 * child_budget:
        try:
            record.update(_measure_bf16_forward(
                shape, corr_impl, forward, variables, img1, img2
            ))
            _emit(record)
        except Exception as e:  # never lose the earlier rows
            print(f"bf16 forward bench failed: {e}", file=sys.stderr)
        def _measure_val_bf16(shape, mixed_precision, corr_impl, variables,
                              precision):
            # The bf16 val row must run under the SAME thread
            # configuration as its f32 sibling or the CPU comparison
            # embeds the known all-cores contention artifact (the reason
            # _run_val_child exists): sub-child with one core reserved
            # on CPU, in-process elsewhere.
            if platform == "cpu":
                spare = child_budget - (time.monotonic() - t0) - 10.0
                out = _run_val_child(
                    shape, corr_impl, min(300.0, spare),
                    precision=precision,
                )
                if out is not None:
                    return out
                print(
                    "bf16 val sub-child yielded nothing; measuring "
                    "in-process (shared XLA pool — expect contention)",
                    file=sys.stderr,
                )
            return _measure_val_loop(
                shape, mixed_precision, corr_impl, variables,
                precision=precision,
            )

        for tag, skip_env, fn in (
            ("val", "BENCH_SKIP_VAL", _measure_val_bf16),
            ("serve", "BENCH_SKIP_SERVE", _measure_serve),
            ("stream", "BENCH_SKIP_STREAM", _measure_stream),
        ):
            if knob_flag(skip_env):
                continue
            if child_budget - (time.monotonic() - t0) < 0.1 * child_budget:
                break
            try:
                rows = fn(shape, mixed_precision, corr_impl, variables,
                          precision="bf16_infer")
                record.update({f"{k}_bf16": v for k, v in rows.items()})
                _emit(record)
            except Exception as e:  # never lose the earlier rows
                print(f"bf16 {tag} bench failed: {e}", file=sys.stderr)
        # bf16_train loop last: it pays a second fwd+bwd compile, the
        # most expensive item in the block.
        if (
            not knob_flag("BENCH_SKIP_TRAIN")
            and child_budget - (time.monotonic() - t0) > 0.25 * child_budget
        ):
            try:
                fields, handles = _measure_train_step(
                    shape, mixed_precision, corr_impl,
                    precision="bf16_train",
                )
                record.update(
                    {f"{k}_bf16": v for k, v in fields.items()}
                )
                # Emit the step row before attempting the loop: the
                # fwd+bwd compile it paid for must survive a loop
                # failure or a watchdog kill mid-loop.
                _emit(record)
                if (
                    child_budget - (time.monotonic() - t0)
                    > 0.1 * child_budget
                ):
                    loop = _measure_train_loop(handles)
                    record.update(
                        {f"{k}_bf16": v for k, v in loop.items()}
                    )
                    _emit(record)
            except Exception as e:  # never lose the earlier rows
                print(f"bf16 train bench failed: {e}", file=sys.stderr)

    # 1080p spatially-sharded row (docs/SHARDING.md; ROADMAP item 4):
    # the flagship onthefly forward at 1088x1920, SPMD over the visible
    # mesh whenever it has >1 device, with the collective-bytes sharding
    # fingerprint and the standard guard counters. Last in line (it uses
    # leftover budget — a 1080p compile + reps must never starve the
    # established rows); reduced iters on CPU; BENCH_SKIP_HIGHRES=1
    # turns it off explicitly, BENCH_MESH="data,spatial" pins the mesh.
    if knob_flag("BENCH_SKIP_HIGHRES"):
        pass
    elif child_budget - (time.monotonic() - t0) > 0.12 * child_budget:
        try:
            record.update(_measure_highres(variables))
            _emit(record)
        except Exception as e:  # never lose the earlier rows
            print(f"highres bench failed: {e}", file=sys.stderr)
        # bf16 composition (ROADMAP item 3's folded follow-up): the same
        # sharded window under the bf16_infer preset.
        if (
            not knob_flag("BENCH_SKIP_BF16")
            and child_budget - (time.monotonic() - t0) > 0.12 * child_budget
        ):
            try:
                rows = _measure_highres(variables, precision="bf16_infer")
                record.update({f"{k}_bf16": v for k, v in rows.items()})
                _emit(record)
            except Exception as e:  # never lose the earlier rows
                print(f"bf16 highres bench failed: {e}", file=sys.stderr)

    # UHD/4K row (docs/PERF.md "Banded dispatch"; ROADMAP item 4's
    # second half): the 2176x3840 single-frame forward the banded corr
    # tier makes servable, guarded like the highres row. Very last in
    # budget order — a 4K compile must never starve anything else;
    # BENCH_SKIP_UHD=1 turns it off, BENCH_UHD_* tune shape/iters/reps.
    if knob_flag("BENCH_SKIP_UHD"):
        pass
    elif child_budget - (time.monotonic() - t0) > 0.12 * child_budget:
        try:
            record.update(_measure_uhd(variables))
            _emit(record)
        except Exception as e:  # never lose the earlier rows
            print(f"uhd bench failed: {e}", file=sys.stderr)

    # Iteration-pipeline streaming row (docs/SHARDING.md "Pipeline
    # axis"; ROADMAP item 2): micro-batches streamed through scan
    # segments over the pipe mesh axis, with the collective-permute
    # handoff fingerprint, per-segment ledger costs, and the standard
    # guard counters. Budget-gated like the other tail rows;
    # BENCH_SKIP_PIPELINE=1 turns it off explicitly.
    if knob_flag("BENCH_SKIP_PIPELINE"):
        pass
    elif child_budget - (time.monotonic() - t0) > 0.12 * child_budget:
        try:
            record.update(_measure_pipeline(variables))
            _emit(record)
        except Exception as e:  # never lose the earlier rows
            print(f"pipeline bench failed: {e}", file=sys.stderr)

    # Early-exit row (docs/PERF.md "Early exit"; ROADMAP item 5's first
    # half): the convergence-detection forward vs its full-budget twin
    # over a mixed-resolution zipf stream, with the EPE-vs-speedup pair
    # flip_recommendations judges against the pinned quality budget.
    # Small shapes, so it fits a tail-row budget slice;
    # BENCH_SKIP_EARLYEXIT=1 turns it off explicitly.
    if knob_flag("BENCH_SKIP_EARLYEXIT"):
        pass
    elif child_budget - (time.monotonic() - t0) > 0.12 * child_budget:
        try:
            record.update(_measure_earlyexit(variables))
            _emit(record)
        except Exception as e:  # never lose the earlier rows
            print(f"earlyexit bench failed: {e}", file=sys.stderr)


def _measure_bf16_forward(
    shape: dict, corr_impl: str, f32_forward, variables: dict,
    img1, img2,
) -> dict:
    """The bf16_infer test-mode forward at the bench shape: throughput
    (`pairs_per_sec_bf16`), guard counters over the timed reps
    (`fwd_bf16_recompiles` / `fwd_bf16_host_transfers` — 0 in steady
    state, same machinery as the f32 rows), and the parity field
    (`bf16_forward_epe_vs_f32`: mean EPE between the bf16 and f32
    predictions on the SAME inputs/variables) next to the test-pinned
    budget, so flip_recommendations can judge the row without importing
    jax."""
    import jax
    import numpy as np

    from raft_ncup_tpu.analysis.guards import (
        GuardStats,
        RecompileWatchdog,
        forbid_host_transfers,
    )
    from raft_ncup_tpu.config import flagship_config
    from raft_ncup_tpu.models.raft import get_model
    from raft_ncup_tpu.precision import FORWARD_EPE_BUDGET
    from raft_ncup_tpu.utils.profiling import measure_throughput_detailed

    strict = knob_flag("BENCH_STRICT_GUARDS")
    iters = shape["iters"]
    model = get_model(
        flagship_config(
            dataset="sintel", corr_impl=corr_impl, precision="bf16_infer"
        )
    )

    def fwd(v, a, b):
        return model.apply(v, a, b, iters=iters, test_mode=True)

    bf16_forward = jax.jit(fwd)
    # Parity on the warm executables (one extra f32 call, both warm
    # before the timed window).
    ref = np.asarray(jax.device_get(f32_forward(variables, img1, img2)[1]))
    out = np.asarray(
        jax.device_get(bf16_forward(variables, img1, img2)[1])
    )
    epe = float(np.sqrt(((out - ref) ** 2).sum(-1)).mean())
    # Pre-warm the sync path's tiny scalar-index program OUTSIDE the
    # guarded window (its first use would otherwise count as a
    # steady-state compile).
    jax.device_get(bf16_forward(variables, img1, img2)[1][0, 0, 0, 0])

    stats = GuardStats()
    with RecompileWatchdog() as wd, forbid_host_transfers(
        stats, raise_on_violation=strict
    ):
        rate, rep_times = measure_throughput_detailed(
            lambda: bf16_forward(variables, img1, img2),
            warmup=1,
            reps=3,
            sync=lambda o: np.asarray(jax.device_get(o[1][0, 0, 0, 0])),
        )
    return {
        "pairs_per_sec_bf16": round(shape["batch"] * rate, 4),
        "bf16_rep_ms": [round(t * 1e3, 1) for t in rep_times],
        "bf16_forward_epe_vs_f32": round(epe, 5),
        "bf16_epe_budget": FORWARD_EPE_BUDGET,
        "fwd_bf16_recompiles": wd.count,
        "fwd_bf16_host_transfers": stats.host_transfers,
    }


def _measure_train_step(
    shape: dict, mixed_precision: bool, corr_impl: str,
    precision: str = "f32",
) -> tuple[dict, dict]:
    """Time one optimizer step (fwd+bwd+update) at the bench shape,
    reference workload anchor: train.py:201-225.

    Returns ``(record_fields, handles)`` — handles carry the compiled step
    and the carried state so the pipelined-loop row reuses the same
    executable (no second multi-minute compile on the CPU host)."""
    import jax
    import numpy as np

    from raft_ncup_tpu.config import TrainConfig, flagship_config
    from raft_ncup_tpu.parallel.step import make_synthetic_batch, make_train_step
    from raft_ncup_tpu.training.state import create_train_state
    from raft_ncup_tpu.utils.profiling import measure_throughput_detailed

    B, H, W = shape["batch"], shape["height"], shape["width"]
    model_cfg = flagship_config(
        dataset="sintel", mixed_precision=mixed_precision,
        corr_impl=corr_impl, precision=precision,
    )
    train_cfg = TrainConfig(
        stage="sintel", batch_size=B, image_size=(H, W),
        iters=shape["iters"], num_steps=100, precision=precision,
    )
    model, state = create_train_state(
        jax.random.PRNGKey(0), model_cfg, train_cfg,
        image_shape=(1, H, W, 3),
    )
    step = make_train_step(model, train_cfg)
    kbatch, krng = jax.random.split(jax.random.PRNGKey(7))
    batch = make_synthetic_batch(kbatch, B, H, W)

    # donate_argnums=0 consumes `state`; rebuild the call each rep with the
    # carried state so timing reflects the steady-state step.
    holder = {"state": state}

    def one_step():
        holder["state"], metrics = step(holder["state"], batch, krng)
        return metrics

    rate, rep_times = measure_throughput_detailed(
        one_step, warmup=2, reps=3,
        sync=lambda m: np.asarray(m["loss"]),
    )
    fields = {
        "train_pairs_per_sec": round(B * rate, 4),
        "train_ms_per_step": round(1000.0 / rate, 1),
        "train_rep_ms": [round(t * 1e3, 1) for t in rep_times],
    }
    handles = {
        "step": step, "state": holder["state"], "krng": krng,
        "B": B, "H": H, "W": W,
    }
    return fields, handles


def _measure_train_loop(handles: dict, steps: int | None = None) -> dict:
    """Wall-clock N PIPELINED steps — the steady-state train.py loop.

    Host batches flow through the DevicePrefetcher (transfer overlapped
    with compute), the per-step loss accumulates ON DEVICE (the Logger
    contract: no float()/device_get between summary boundaries), and the
    host syncs ONCE at the end of the window. ``train_ms_per_step`` above
    measures the same compiled step with a per-step sync on a pre-placed
    batch, so ``train_loop_ms_per_step - train_ms_per_step`` is the
    input + sync stall the async pipeline failed to hide; <= 0 means the
    overlap is complete and the dispatch-pipelined loop beats the
    serialized one.

    The window runs under the runtime guards (analysis/guards.py), so the
    record tracks the INVARIANT next to the speed:
    ``train_loop_recompiles`` (XLA compiles inside the steady-state
    window; 0 when avals are stable) and ``train_loop_host_transfers``
    (implicit device→host pulls; 0 when the loop is sync-free — the
    single end-of-window pull goes through the sanctioned explicit
    ``jax.device_get``). Guards count by default; ``BENCH_STRICT_GUARDS=1``
    makes a violation raise instead of recording a nonzero counter.
    """
    import jax
    import numpy as np

    from raft_ncup_tpu.analysis.guards import (
        GuardStats,
        RecompileWatchdog,
        forbid_host_transfers,
    )
    from raft_ncup_tpu.data.device_prefetch import DevicePrefetcher

    step, krng = handles["step"], handles["krng"]
    B, H, W = handles["B"], handles["H"], handles["W"]
    steps = steps or knob_int("BENCH_TRAIN_LOOP_STEPS")
    strict = knob_flag("BENCH_STRICT_GUARDS")

    rng = np.random.default_rng(11)

    def host_batches(n: int):
        # Fresh host arrays every step so the prefetcher really transfers
        # per step. float32 images to match make_synthetic_batch's avals —
        # uint8 would change the jit signature and recompile the step,
        # which on the 1-core CPU host costs minutes.
        for _ in range(n):
            yield {
                "image1": (rng.random((B, H, W, 3), np.float32) * 255.0),
                "image2": (rng.random((B, H, W, 3), np.float32) * 255.0),
                "flow": rng.standard_normal((B, H, W, 2)).astype(np.float32),
                "valid": np.ones((B, H, W), np.float32),
            }

    holder = {"state": handles["state"]}
    stats = GuardStats()
    with DevicePrefetcher(host_batches(steps + 1), depth=2) as pf:
        # One warmup step: fills the pipeline and proves the executable is
        # reused (same avals as the per-step row — no recompile).
        holder["state"], m = step(holder["state"], next(pf), krng)
        m["loss"] + m["loss"]  # pre-warm the accumulator's scalar add
        np.asarray(m["loss"])
        with RecompileWatchdog() as wd, forbid_host_transfers(
            stats, raise_on_violation=strict
        ):
            loss_acc = None
            t0 = time.perf_counter()
            for _ in range(steps):
                holder["state"], metrics = step(
                    holder["state"], next(pf), krng
                )
                loss_acc = (
                    metrics["loss"] if loss_acc is None
                    else loss_acc + metrics["loss"]
                )
            jax.device_get(loss_acc)  # the window's single SANCTIONED sync
            dt = time.perf_counter() - t0
    # Hand the LIVE carried state back: the loop's donated steps consumed
    # the buffers `handles["state"]` pointed at, and the checkpoint row
    # needs a live pytree to save.
    handles["state"] = holder["state"]
    return {
        "train_loop_pairs_per_sec": round(B * steps / dt, 4),
        "train_loop_ms_per_step": round(dt * 1000.0 / steps, 1),
        "train_loop_steps": steps,
        "train_loop_recompiles": wd.count,
        "train_loop_host_transfers": stats.host_transfers,
    }


def _measure_val_loop(
    shape: dict, mixed_precision: bool, corr_impl: str, variables: dict,
    n_batches: int | None = None, precision: str = "f32",
) -> dict:
    """Wall-clock the PIPELINED eval loop vs the per-batch-synced one —
    the steady-state validation path (docs/PERF.md "Eval pipeline").

    Both windows run the SAME warm compiled executable — the test-mode
    forward with the on-device EPE fold (inference/metrics.py) — over
    the same synthetic frames (style='rigid': its cv2 render cost
    stands in for the real validators' PNG decode + staging). Only the
    LOOP STRUCTURE differs:

    - **per-batch-synced** (``val_synced_ms_per_pair``): a FULLY
      serialized loop — decode/stage inline on the dispatch thread, one
      ``jax.device_get`` per batch. This brackets the total benefit of
      the async structure, not this repo's increment alone: the
      pre-refactor validators already overlapped decode via a prefetch
      pool but still paid the per-batch sync + full-field pull.
    - **pipelined** (``val_ms_per_pair``): the refactored loop —
      decode/stage on worker threads ``depth`` batches ahead
      (EvalPipeline), dispatch depth bounded per backend
      (DispatchThrottle), ONE sanctioned ``jax.device_get`` of the
      accumulator at the window end.

    ``val_stall_ms_per_pair = val_synced_ms_per_pair - val_ms_per_pair``
    is the per-pair decode + sync stall the async pipeline RECOVERED
    (positive = the pipelined loop beats the serialized one; note the
    sign runs opposite to ``train_loop_stall_ms_per_step``, whose
    comparator EXCLUDES input work — here the comparator contains it).
    Windows interleave and repeat ``BENCH_VAL_LOOP_REPS`` times with
    the MINIMUM kept: the recoverable stall is a few percent of a pair
    at CPU shapes, and min-of-reps filters shared-host scheduling noise
    a single window cannot.

    On the CPU backend this function is re-entered in a sub-child whose
    XLA host pool leaves one core free (``_val_child_env``): with the
    default pool (= all cores) the decode thread can only "overlap" by
    stealing compute cores, which makes overlap physically impossible
    on a saturated host — the serving configuration reserves input
    cores, and the row measures THAT configuration.

    The guarded pipelined rep fills ``val_loop_recompiles`` and
    ``val_loop_host_transfers``; both must be 0 in steady state — the
    eval loop inherits the train loop's sync-free/recompile-free
    invariants. ``BENCH_STRICT_GUARDS=1`` makes a violation raise.
    """
    import contextlib

    import jax
    import numpy as np

    from raft_ncup_tpu.analysis.guards import (
        GuardStats,
        RecompileWatchdog,
        forbid_host_transfers,
    )
    from raft_ncup_tpu.config import flagship_config
    from raft_ncup_tpu.data.synthetic import SyntheticFlowDataset
    from raft_ncup_tpu.inference import metrics as metrics_mod
    from raft_ncup_tpu.inference.pipeline import (
        DispatchThrottle,
        EvalPipeline,
        ShapeCachedForward,
    )
    from raft_ncup_tpu.models.raft import get_model

    B, H, W = shape["batch"], shape["height"], shape["width"]
    iters = shape["iters"]
    n_batches = n_batches or knob_int("BENCH_VAL_LOOP_BATCHES")
    # Batch 0 of every window is the untimed warm step, so the timed
    # region needs at least one more batch to exist.
    n_batches = max(2, n_batches)
    reps = knob_int("BENCH_VAL_LOOP_REPS")
    strict = knob_flag("BENCH_STRICT_GUARDS")

    model = get_model(
        flagship_config(
            dataset="sintel", mixed_precision=mixed_precision,
            corr_impl=corr_impl, precision=precision,
        )
    )
    fwd = ShapeCachedForward(model, variables)
    dataset = SyntheticFlowDataset(
        (H, W), length=B * n_batches, seed=77, style="rigid"
    )

    def stage(group: list) -> tuple:
        return {
            "image1": np.stack([s["image1"] for s in group]).astype(np.float32),
            "image2": np.stack([s["image2"] for s in group]).astype(np.float32),
            "flow": np.stack([s["flow"] for s in group]).astype(np.float32),
        }, {}

    # Warm-up outside all windows: compile THE executable both windows
    # share, run one throwaway pipeline round (first worker-thread
    # spin-up in a process costs a few hundred ms), and prime the tiny
    # init_acc program.
    warm_batch, _ = stage([dataset.sample(i) for i in range(B)])
    acc = fwd.metrics(
        warm_batch, iters=iters, acc=metrics_mod.init_acc("epe"), kind="epe"
    )
    jax.device_get(acc)
    warm_ds = SyntheticFlowDataset((H, W), length=B, seed=78, style="rigid")
    with EvalPipeline(warm_ds, stage, batch_size=B, depth=2) as pipe:
        for _batch, _meta in pipe:
            pass

    # Both windows time the STEADY STATE: batch 0 is a warm step
    # executed before the clock starts (the train-loop row's contract —
    # it fills the pipeline / absorbs first-dispatch jitter), so the
    # timed region covers n_batches - 1 identical steady iterations.
    def synced_window() -> float:
        """Fully serialized comparator: inline decode/stage, same
        executable, one pull per batch (see the bracketing note in the
        enclosing docstring)."""
        acc = metrics_mod.init_acc("epe")
        batch, _ = stage([dataset.sample(k) for k in range(B)])
        acc = fwd.metrics(batch, iters=iters, acc=acc, kind="epe")
        jax.device_get(acc)
        t0 = time.perf_counter()
        for g0 in range(B, len(dataset), B):
            batch, _ = stage([dataset.sample(g0 + k) for k in range(B)])
            acc = fwd.metrics(batch, iters=iters, acc=acc, kind="epe")
            jax.device_get(acc)
        return time.perf_counter() - t0

    def pipelined_window(guarded: bool):
        stats = GuardStats()
        wd = None
        with EvalPipeline(dataset, stage, batch_size=B, depth=2) as pipe:
            guard = (
                forbid_host_transfers(stats, raise_on_violation=strict)
                if guarded else contextlib.nullcontext()
            )
            watchdog = RecompileWatchdog() if guarded else contextlib.nullcontext()
            with watchdog as wd, guard:
                acc = metrics_mod.init_acc("epe")
                throttle = DispatchThrottle()
                batch, _meta = next(iter(pipe))  # warm step: fills pipeline
                acc = fwd.metrics(batch, iters=iters, acc=acc, kind="epe")
                throttle.push(acc)
                t0 = time.perf_counter()
                for batch, _meta in pipe:
                    acc = fwd.metrics(batch, iters=iters, acc=acc, kind="epe")
                    throttle.push(acc)
                jax.device_get(acc)
                dt = time.perf_counter() - t0
        return dt, stats, wd

    # Guarded steady-state rep first: fills the invariant counters and is
    # EXCLUDED from timing (the pull-guard patches add per-call checks).
    _, g_stats, g_wd = pipelined_window(guarded=True)
    recompiles = g_wd.count if g_wd is not None else 0
    transfers = g_stats.host_transfers
    # Timed windows interleave synced/pipelined so slow drift on a shared
    # host (frequency scaling, co-tenants) hits both PAIRED windows
    # equally; the stall estimate is the MEDIAN of per-rep deltas — the
    # robust estimator of a systematic shift under common drift (a
    # min-of-each-side difference instead compares two different noise
    # draws and flips sign at CPU-scale margins).
    synced_dts, pipe_dts = [], []
    for _ in range(max(1, reps)):
        synced_dts.append(synced_window())
        dt, _, _ = pipelined_window(guarded=False)
        pipe_dts.append(dt)

    def med(xs: list) -> float:
        xs = sorted(xs)
        m = len(xs) // 2
        return xs[m] if len(xs) % 2 else 0.5 * (xs[m - 1] + xs[m])

    pairs = B * (n_batches - 1)  # batch 0 of each window is the warm step
    pipe_ms = med(pipe_dts) * 1000.0 / pairs
    synced_ms = med(synced_dts) * 1000.0 / pairs
    stall_ms = med(
        [(s - p) * 1000.0 / pairs for s, p in zip(synced_dts, pipe_dts)]
    )
    return {
        "val_pairs_per_sec": round(1000.0 / pipe_ms, 4),
        "val_ms_per_pair": round(pipe_ms, 1),
        "val_synced_ms_per_pair": round(synced_ms, 1),
        "val_stall_ms_per_pair": round(stall_ms, 1),
        "val_loop_batches": n_batches,
        "val_loop_reps": reps,
        "val_loop_recompiles": recompiles,
        "val_loop_host_transfers": transfers,
    }


def _parse_mesh_env() -> tuple | None:
    """The ONE parser for the ``BENCH_MESH`` "data,spatial" spec (set by
    ``--mesh``): validated positive int pair or None, bad specs loudly
    ignored. Every mesh-aware row goes through this — three hand-rolled
    parsers would mean three divergent failure modes."""
    spec = knob_raw("BENCH_MESH")
    if not spec:
        return None
    try:
        data, spatial = (int(x) for x in spec.split(","))
    except ValueError:
        print(f"ignoring bad BENCH_MESH {spec!r} (want DATA,SPATIAL)",
              file=sys.stderr)
        return None
    if data < 1 or spatial < 1:
        print(f"ignoring bad BENCH_MESH {spec!r} (sizes must be >= 1)",
              file=sys.stderr)
        return None
    return (data, spatial)


def _bench_mesh_spec(batch_sizes: tuple) -> tuple | None:
    """The (data, spatial) mesh the serving/streaming rows run under
    when ``BENCH_MESH`` pins one (None otherwise). The rows' batch
    programs shard their batch axis over `data`, so a data size their
    batch sizes cannot divide is refused loudly rather than passed on
    to fail mid-warmup."""
    spec = _parse_mesh_env()
    if spec is None or spec == (1, 1):
        return None
    data, spatial = spec
    if any(b % data for b in batch_sizes):
        print(
            f"BENCH_MESH {spec}: data={data} does not divide batch "
            f"sizes {batch_sizes}; running this row unsharded",
            file=sys.stderr,
        )
        return None
    return spec


def _measure_serve(
    shape: dict, mixed_precision: bool, corr_impl: str, variables: dict,
    n_requests: int | None = None, precision: str = "f32",
) -> dict:
    """Steady-state serving latency/throughput through the FlowServer
    front-end (serving/server.py; docs/SERVING.md).

    The window is OPEN-LOOP and deliberately under capacity: requests
    arrive at ~1.3x the calibrated per-pair service time, so the row
    measures the steady state the latency SLO is written against —
    admission + staging + micro-batch dispatch + drain-worker pull —
    not queueing collapse (the burst/shed/degrade behaviors are pinned
    functionally by tests/test_serving.py, not timed here). p50/p99 are
    nearest-rank over per-request submit→complete latencies;
    ``serve_ok`` records the sample count behind them (``serve_requests``
    is the offered count).

    The whole window runs under the runtime guards: ``serve_recompiles``
    counts XLA compiles after the warmup compiled the full executable
    set (must be 0 — the bounded (batch, iters) program set is the
    recompile-free contract under load), ``serve_host_transfers`` counts
    IMPLICIT device→host pulls (must be 0 — each batch's single result
    pull rides the sanctioned explicit ``jax.device_get`` in the
    AsyncDrain worker). ``serve_shed``/``serve_timeouts``/``serve_errors``
    must also be 0 here: a row that shed load measured backpressure, not
    service, and a window that errored is incomplete.
    BENCH_STRICT_GUARDS=1 makes guard violations raise.

    On CPU the dispatcher and XLA share the host pool; with
    ``inflight=1`` (the CPU default) programs serialize, so the number
    is an honest single-stream CPU figure, clearly labeled by the
    baseline key. On accelerators the same code overlaps staging with
    device compute.
    """
    from raft_ncup_tpu.analysis.guards import (
        GuardStats,
        RecompileWatchdog,
        forbid_host_transfers,
    )
    from raft_ncup_tpu.config import ServeConfig, flagship_config
    from raft_ncup_tpu.models.raft import get_model
    from raft_ncup_tpu.observability import Telemetry
    from raft_ncup_tpu.serving import FlowServer, SyntheticTraffic, replay

    B, H, W = shape["batch"], shape["height"], shape["width"]
    iters = shape["iters"]
    n = n_requests or knob_int("BENCH_SERVE_REQUESTS")
    strict = knob_flag("BENCH_STRICT_GUARDS")
    # Telemetry-off comparison window (the observer-overhead row;
    # docs/OBSERVABILITY.md methodology). BENCH_SKIP_TELEMETRY_COMPARE=1
    # skips it (fields absent); the bf16 twin skips it too — the
    # observer-overhead question is precision-independent and the f32
    # row already answers it.
    tel_compare = (
        not knob_flag("BENCH_SKIP_TELEMETRY_COMPARE")
        and precision == "f32"
    )

    # Two budget levels at the bench shape: the idle-load level is the
    # row's headline; the lower level exists so the warmup compiles the
    # REAL executable-set size the server would hold in production.
    levels = (iters, max(1, iters // 2))
    cfg = ServeConfig(
        queue_capacity=max(8, n),
        batch_sizes=(1, 2),
        iter_levels=levels,
        recover_patience=2,
        precision=precision,
        mesh=_bench_mesh_spec(batch_sizes=(1, 2)),
    )
    model = get_model(
        flagship_config(
            dataset="sintel", mixed_precision=mixed_precision,
            corr_impl=corr_impl,
        )
    )
    # Fresh telemetry hub per row: the window's counters/spans are
    # isolated from the process default and from other rows. The
    # declared serving SLOs ride along (observability/slo.py): the row
    # stamps their verdict block so flip_recommendations can tell a
    # clean steady-state window from one that was degraded while the
    # latencies were measured.
    from raft_ncup_tpu.observability import SloEngine, serve_slos

    tel = Telemetry()
    tel.slo = SloEngine(serve_slos(), tel)
    server = FlowServer(model, variables, cfg, telemetry=tel)
    try:
        server.warmup((H, W))
        # Calibrate the open-loop rate on the warm top-level executable:
        # a couple of sequential requests give the per-pair service time.
        calib = SyntheticTraffic((H, W), 2, seed=90, style="rigid")
        t0 = time.perf_counter()
        for h in replay(server, calib)[0]:
            h.result(timeout=120.0)
        per_pair = (time.perf_counter() - t0) / 2.0
        interval = per_pair * 1.3

        stats = GuardStats()
        with RecompileWatchdog() as wd, forbid_host_transfers(
            stats, raise_on_violation=strict
        ):
            # Window A — telemetry FULLY ENABLED (counters, spans, queue
            # gauges): the headline serve_* numbers, and the guard
            # counters prove 0 recompiles / 0 implicit transfers hold
            # under full tracing. Counter deltas bracket the window so
            # the sanctioned-get consistency check (flip_recommendations)
            # compares like with like.
            batches_before = server.stats.batches
            pulls_before = tel.counter_value("serve_drain_pulls_total")
            tel.slo.evaluate()  # baseline sample for the window's burn
            traffic = SyntheticTraffic(
                (H, W), n, seed=91, interval_s=interval, style="rigid"
            )
            t0 = time.perf_counter()
            handles, _ = replay(server, traffic)
            responses = [h.result(timeout=120.0) for h in handles]
            dt = time.perf_counter() - t0
            batches_in_window = server.stats.batches - batches_before
            pulls_in_window = int(
                tel.counter_value("serve_drain_pulls_total") - pulls_before
            )
            # The window's SLO verdicts + health state, evaluated inside
            # the guard scope (the evaluation itself must add no sync).
            tel.slo.evaluate()
            slo_snap = tel.slo.snapshot()
            health_state = server.health.state
            stages = server.report()["stages"]
            # Snapshot the window-A health counters BEFORE window B: the
            # record's shed/timeouts/errors/budget_drops must describe
            # the window the headline latencies came from, not absorb a
            # later off-window hiccup (flip_recommendations disqualifies
            # rows on these).
            win_a = {
                "shed": server.stats.shed,
                "timeouts": server.stats.timeouts,
                "errors": server.stats.errors,
                "budget_drops": server.budget.drops,
            }
            # Window B — SAME warm server, same rate, telemetry
            # DISABLED: the p50 delta is the measured observer overhead.
            responses_off, dt_off = [], None
            if tel_compare:
                tel.enabled = False
                try:
                    traffic_off = SyntheticTraffic(
                        (H, W), n, seed=94, interval_s=interval,
                        style="rigid",
                    )
                    t0 = time.perf_counter()
                    handles_off, _ = replay(server, traffic_off)
                    responses_off = [
                        h.result(timeout=120.0) for h in handles_off
                    ]
                    dt_off = time.perf_counter() - t0
                finally:
                    tel.enabled = True
    finally:
        server.drain()

    from raft_ncup_tpu.serving import nearest_rank_ms

    lat = [
        r.latency_s for r in responses if r.ok and r.latency_s is not None
    ]
    sstats = server.stats
    if not lat:
        raise RuntimeError(f"no ok responses in serve window: "
                           f"{sstats.summary()}")
    record = {
        "serve_pairs_per_sec": round(len(lat) / dt, 4) if dt > 0 else 0.0,
        "serve_p50_ms": nearest_rank_ms(lat, 0.50),
        "serve_p99_ms": nearest_rank_ms(lat, 0.99),
        "serve_requests": n,
        "serve_ok": len(lat),
        "serve_interval_ms": round(interval * 1e3, 1),
        "serve_iters": levels[0],
        "serve_iters_budgeted": levels[0],
        "serve_shed": win_a["shed"],
        "serve_timeouts": win_a["timeouts"],
        "serve_errors": win_a["errors"],
        "serve_budget_drops": win_a["budget_drops"],
        "serve_mesh": server.report()["mesh"],
        "serve_recompiles": wd.count,
        "serve_host_transfers": stats.host_transfers,
        # Telemetry snapshot consistency (flip_recommendations): the
        # drain worker's pull counter vs the dispatcher's batch count —
        # two independent measurements of the same window that must
        # agree on a clean run.
        "serve_batches": batches_in_window,
        "serve_sanctioned_gets": pulls_in_window,
        # Per-stage p50/p99 breakdown from the span tracer (includes
        # warm calibration traffic; the stage shape, not the headline).
        "serve_stages": stages,
        # Health/SLO verdict block (observability/; docs/OBSERVABILITY.md):
        # the declared SLO set's verdicts over this window and the
        # server's final health state — flip_recommendations reads both.
        "serve_health": health_state,
        "serve_slo_pages": slo_snap["pages_total"],
        "serve_slo": slo_snap["verdicts"],
    }
    # Executed-iterations stats (docs/PERF.md "Early exit"): when the
    # RAFT_NCUP_EARLYEXIT knob had convergence detection live during
    # this window, the server's per-request serve_exec_iters histogram
    # holds the real counts; otherwise every request ran its full
    # budget and executed == budgeted (worst case, stated explicitly).
    exec_hist = tel.registry.get("serve_exec_iters")
    if exec_hist is not None and exec_hist.count:
        record["serve_iters_executed_mean"] = round(
            exec_hist.sum_ms / exec_hist.count, 3
        )
        record["serve_iters_executed_p50"] = exec_hist.percentile_ms(0.50)
        record["serve_iters_executed_p99"] = exec_hist.percentile_ms(0.99)
    else:
        record["serve_iters_executed_mean"] = float(levels[0])
        record["serve_iters_executed_p50"] = levels[0]
        record["serve_iters_executed_p99"] = levels[0]
    # Executable cost facts from the ledger the warmup just fed
    # (inference/costs.py): the headline batch-1 top-level executable's
    # XLA flops, and MFU against the backend's peak table — non-null on
    # CPU today, real TPU MFU the moment a chip answers.
    from raft_ncup_tpu.inference import costs as costs_mod

    if server.warmed:
        ph, pw = server.warmed[0][0], server.warmed[0][1]
        # The policy fingerprint disambiguates the f32 and bf16 serve
        # rows' entries in the shared process-wide ledger — same shape
        # and iters, different executables with different flops.
        entry = server._fwd.costs.lookup(
            kind="forward", shape=(1, ph, pw, 3), iters=levels[0],
            policy=server._fwd.policy.fingerprint(),
        )
        if entry is not None and entry.get("flops"):
            import jax as _jax

            peak = costs_mod.peak_flops(
                _jax.default_backend(),
                device_kind=getattr(
                    _jax.devices()[0], "device_kind", None
                ),
                tpu_gen=os.environ.get("PALLAS_AXON_TPU_GEN"),
            )
            record["serve_flops_per_pair"] = round(entry["flops"], 0)
            record["serve_mfu"] = costs_mod.mfu(
                entry["flops"], record["serve_pairs_per_sec"], peak
            )
    lat_off = [
        r.latency_s
        for r in responses_off
        if r.ok and r.latency_s is not None
    ]
    if lat_off and dt_off:
        p50_on = record["serve_p50_ms"]
        p50_off = nearest_rank_ms(lat_off, 0.50)
        record["serve_p50_ms_notelemetry"] = p50_off
        record["serve_p99_ms_notelemetry"] = nearest_rank_ms(lat_off, 0.99)
        if p50_off:
            record["serve_telemetry_overhead_pct"] = round(
                100.0 * (p50_on - p50_off) / p50_off, 2
            )
    return record


def _measure_stream(
    shape: dict, mixed_precision: bool, corr_impl: str, variables: dict,
    n_frames: int | None = None, precision: str = "f32",
) -> dict:
    """Steady-state multi-stream video throughput through the
    StreamEngine (streaming/engine.py; docs/STREAMING.md).

    The window multiplexes ``BENCH_STREAM_STREAMS`` (default 4)
    concurrent synthetic streams into the batched warm-start step and
    measures frames/sec plus per-frame submit→complete latency. Like
    the serve row it is open-loop and deliberately under capacity
    (arrivals at ~1.3x the calibrated per-frame service time) — the
    admission/eviction/anomaly behaviors are pinned functionally by
    tests/test_streaming.py, not timed here.

    Guards: ``stream_recompiles`` counts XLA compiles after warmup
    compiled the per-batch-size step set (must be 0 — slot reuse,
    cold/warm transitions, and anomaly resets all ride the SAME
    executables); ``stream_host_transfers`` counts implicit d2h pulls
    (must be 0 — each batch's flow+flags pull is the sanctioned
    explicit ``jax.device_get`` in the AsyncDrain worker; the
    warm-start chain itself never leaves the device).
    ``stream_shed``/``stream_errors``/``stream_resets`` must be 0 here:
    a window that shed measured backpressure and a window that reset
    measured anomaly handling, not service. Slot-table occupancy stats
    (mean/peak over dispatched batches) land in the record so a future
    capacity flip has data. BENCH_STRICT_GUARDS=1 makes guard
    violations raise.
    """
    from raft_ncup_tpu.analysis.guards import (
        GuardStats,
        RecompileWatchdog,
        forbid_host_transfers,
    )
    from raft_ncup_tpu.config import StreamConfig, flagship_config
    from raft_ncup_tpu.models.raft import get_model
    from raft_ncup_tpu.observability import Telemetry
    from raft_ncup_tpu.serving import nearest_rank_ms
    from raft_ncup_tpu.streaming import (
        StreamEngine,
        StreamTraffic,
        replay_streams,
    )

    B, H, W = shape["batch"], shape["height"], shape["width"]
    iters = shape["iters"]
    n_streams = knob_int("BENCH_STREAM_STREAMS")
    frames = n_frames or knob_int("BENCH_STREAM_FRAMES")
    strict = knob_flag("BENCH_STRICT_GUARDS")

    cfg = StreamConfig(
        capacity=n_streams,
        frame_hw=(H, W),
        iters=iters,
        batch_sizes=(1, 2, 4),
        queue_capacity=max(8, n_streams * frames),
        precision=precision,
        mesh=_bench_mesh_spec(batch_sizes=(1, 2, 4)),
    )
    model = get_model(
        flagship_config(
            dataset="sintel", mixed_precision=mixed_precision,
            corr_impl=corr_impl,
        )
    )
    # Fresh hub for bench-window isolation, with the declared streaming
    # SLOs attached so the row stamps their verdict block (see the
    # serve row).
    from raft_ncup_tpu.observability import SloEngine, stream_slos

    tel = Telemetry()
    tel.slo = SloEngine(stream_slos(n_streams), tel)
    engine = StreamEngine(model, variables, cfg, telemetry=tel)
    try:
        engine.warmup()
        # Calibrate per-frame service time on the warm executables.
        calib = StreamTraffic((H, W), 1, 2, seed=92, style="rigid")
        t0 = time.perf_counter()
        for h in replay_streams(engine, calib)[0]:
            h.result(timeout=120.0)
        per_frame = (time.perf_counter() - t0) / 2.0
        interval = per_frame * 1.3
        # Free the calibration stream's slot (and its frame-index
        # history) so the measured window's "stream-0" admits fresh.
        engine.close_stream(calib.stream_id(0))

        stats = GuardStats()
        with RecompileWatchdog() as wd, forbid_host_transfers(
            stats, raise_on_violation=strict
        ):
            # Telemetry fully enabled through the window; counter deltas
            # bracket it for the snapshot-consistency check.
            batches_before = engine.stats.batches
            pulls_before = tel.counter_value("stream_drain_pulls_total")
            tel.slo.evaluate()  # baseline sample for the window's burn
            traffic = StreamTraffic(
                (H, W), n_streams, frames, seed=93,
                interval_s=interval, style="rigid",
            )
            t0 = time.perf_counter()
            handles, _ = replay_streams(engine, traffic)
            responses = [h.result(timeout=120.0) for h in handles]
            dt = time.perf_counter() - t0
            batches_in_window = engine.stats.batches - batches_before
            pulls_in_window = int(
                tel.counter_value("stream_drain_pulls_total")
                - pulls_before
            )
            tel.slo.evaluate()  # window verdicts, inside the guard scope
            slo_snap = tel.slo.snapshot()
            health_state = engine.health.state
        report = engine.report()
    finally:
        engine.drain()

    lat = [
        r.latency_s for r in responses if r.ok and r.latency_s is not None
    ]
    sstats = engine.stats
    if not lat:
        raise RuntimeError(
            f"no ok responses in stream window: {sstats.summary()}"
        )
    return {
        "stream_frames_per_sec": round(len(lat) / dt, 4) if dt > 0 else 0.0,
        "stream_p50_ms": nearest_rank_ms(lat, 0.50),
        "stream_p99_ms": nearest_rank_ms(lat, 0.99),
        "stream_frames": len(handles),
        "stream_ok": len(lat),
        "stream_streams": n_streams,
        "stream_interval_ms": round(interval * 1e3, 1),
        "stream_iters": iters,
        "stream_shed": sstats.shed_streams + sstats.shed_frames,
        "stream_resets": sstats.resets,
        "stream_errors": sstats.errors,
        "stream_evicted": sstats.streams_evicted,
        "stream_occupancy_mean": report["mean_occupancy"],
        "stream_occupancy_peak": report["peak_occupancy"],
        "stream_capacity": n_streams,
        "stream_mesh": report["mesh"],
        "stream_recompiles": wd.count,
        "stream_host_transfers": stats.host_transfers,
        # Snapshot consistency + per-stage breakdown (observability/).
        "stream_batches": batches_in_window,
        "stream_sanctioned_gets": pulls_in_window,
        "stream_stages": report["stages"],
        # Health/SLO verdict block (see the serve row).
        "stream_health": health_state,
        "stream_slo_pages": slo_snap["pages_total"],
        "stream_slo": slo_snap["verdicts"],
    }


def _measure_fleet(shape: dict, corr_impl: str) -> dict:
    """Guarded fleet-tier row (fleet/; docs/FLEET.md): N real serve.py
    replica child processes behind the FleetRouter, measured over the
    same open-loop steady-state discipline as the serve row so
    ``fleet_p50_ms``/``fleet_p99_ms`` read directly against
    ``serve_p50_ms``/``serve_p99_ms`` — the delta is the router hop
    (wire marshalling + socket + supervision), the thing a fleet
    deployment pays per request.

    Honesty gates mirror the serve row at fleet granularity:
    ``fleet_replica_recompiles``/``fleet_replica_host_transfers`` carry
    EVERY replica's guard counters over its service window (serve.py
    replica mode arms RecompileWatchdog + forbid_host_transfers after
    warmup) and must all be 0; ``fleet_shed``/``fleet_errors``/
    ``fleet_failovers`` must be 0 (a window that shed or failed over
    measured robustness, not service); drain-contract violations from
    the supervisor disqualify the row. Per-replica occupancy
    (``fleet_per_replica_completed``) makes routing skew visible.

    The row spawns real processes: BENCH_FLEET_REPLICAS (default 2)
    bounds the fleet, BENCH_FLEET_REQUESTS (default 12) the window, and
    BENCH_SKIP_FLEET=1 turns the row off.
    """
    import numpy as np

    from raft_ncup_tpu.config import ServeConfig
    from raft_ncup_tpu.data.synthetic import SyntheticFlowDataset
    from raft_ncup_tpu.fleet import (
        FleetConfig,
        FleetRouter,
        ReplicaSupervisor,
    )
    from raft_ncup_tpu.observability import Telemetry
    from raft_ncup_tpu.serving import nearest_rank_ms

    H, W = shape["height"], shape["width"]
    iters = shape["iters"]
    n_replicas = knob_int("BENCH_FLEET_REPLICAS")
    n = knob_int("BENCH_FLEET_REQUESTS")
    platform = os.environ.get("_BENCH_FORCE_PLATFORM") or "cpu"

    import tempfile

    base = tempfile.mkdtemp(prefix="bench_fleet_")
    cfg = FleetConfig(
        base_dir=base,
        n_replicas=n_replicas,
        size_hw=(H, W),
        # One iteration level and a small batch set: the row measures
        # the router hop, not the executable-set arithmetic the serve
        # row already covers — and every replica pays its own warmup.
        serve=ServeConfig(
            queue_capacity=max(8, n), batch_sizes=(1, 2),
            iter_levels=(iters,), recover_patience=2,
        ),
        stream=None,  # request-only row; stream blast radius is test-pinned
        extra_args=(
            "--model", "raft_nc_dbl", "--corr_impl", corr_impl,
            "--platform", platform,
        ),
        snapshot_interval_s=0.5,
    )
    tel = Telemetry()
    sup = ReplicaSupervisor(cfg, telemetry=tel)
    ds = SyntheticFlowDataset((H, W), length=max(4, n), seed=95,
                              style="rigid")
    try:
        sup.start()  # blocks until every replica's healthz reads ready
        router = FleetRouter(cfg, sup, telemetry=tel)

        def frame(i):
            s = ds.sample(i % len(ds))
            return (np.asarray(s["image1"], np.float32),
                    np.asarray(s["image2"], np.float32))

        # Calibrate the open-loop rate through the full router hop.
        t0 = time.perf_counter()
        for i in range(2):
            img1, img2 = frame(i)
            router.submit(img1, img2).result(timeout=120.0)
        per_pair = (time.perf_counter() - t0) / 2.0
        interval = per_pair * 1.3

        handles = []
        t0 = time.perf_counter()
        for i in range(n):
            img1, img2 = frame(i)
            handles.append(router.submit(img1, img2))
            time.sleep(interval)
        responses = [h.result(timeout=120.0) for h in handles]
        dt = time.perf_counter() - t0
        rreport = router.report()
        # Per-hop latency attribution from the trace propagation
        # (docs/OBSERVABILITY.md): the router-side fleet_hop_* stage
        # histograms — router queue / wire / replica / return — over
        # the whole window, read straight from the hub.
        fleet_hops = {
            k: v
            for k, v in tel.tracer.stage_summary().items()
            if k.startswith("fleet_hop_") or k == "fleet_request"
        }
        # Telemetry-overhead window (the serve row's observer-honesty
        # rule at fleet granularity): the SAME warm fleet replays the
        # same open-loop window with every hub — router's and the
        # replicas', toggled over the wire — disabled; the p50 delta is
        # the fleet's measured observer overhead (≤3% budget, flagged
        # by flip_recommendations). BENCH_SKIP_TELEMETRY_COMPARE=1
        # skips it.
        responses_off, dt_off = [], None
        if not knob_flag("BENCH_SKIP_TELEMETRY_COMPARE"):
            acked = router.set_fleet_telemetry(False, timeout=15.0)
            tel.enabled = False
            try:
                # EVERY replica must ack the toggle: a partially-acked
                # fleet would run the off window with one replica still
                # tracing and record an understated overhead.
                if acked == n_replicas:
                    handles_off = []
                    t0 = time.perf_counter()
                    for i in range(n):
                        img1, img2 = frame(i)
                        handles_off.append(router.submit(img1, img2))
                        time.sleep(interval)
                    responses_off = [
                        h.result(timeout=120.0) for h in handles_off
                    ]
                    dt_off = time.perf_counter() - t0
            finally:
                tel.enabled = True
                router.set_fleet_telemetry(True, timeout=15.0)
        router.drain()
    finally:
        reports = sup.stop()

    lat = [
        r.latency_s for r in responses if r.ok and r.latency_s is not None
    ]
    if not lat:
        raise RuntimeError(
            f"no ok responses in fleet window: {rreport['stats']}"
        )
    per_replica = {
        i: (reports.get(i) or {}).get("report") or {}
        for i in range(n_replicas)
    }
    sup_report = sup.report()
    record = {
        "fleet_pairs_per_sec": round(len(lat) / dt, 4) if dt > 0 else 0.0,
        "fleet_p50_ms": nearest_rank_ms(lat, 0.50),
        "fleet_p99_ms": nearest_rank_ms(lat, 0.99),
        "fleet_requests": n,
        "fleet_ok": len(lat),
        "fleet_replicas": n_replicas,
        "fleet_interval_ms": round(interval * 1e3, 1),
        "fleet_iters": iters,
        "fleet_shed": rreport["stats"]["shed"],
        "fleet_errors": sum(
            1 for r in responses if r.status == "error"
        ),
        # Replica-side timeouts/rejections shrink the latency sample
        # silently unless recorded — the serve row's honesty rule at
        # fleet granularity (flip gates on them).
        "fleet_timeouts": sum(
            1 for r in responses if r.status == "timeout"
        ),
        "fleet_rejected": sum(
            1 for r in responses if r.status == "rejected"
        ),
        "fleet_failovers": rreport["stats"]["failovers"],
        "fleet_deaths": sup_report["deaths"],
        "fleet_restarts": sup_report["restarts"],
        "fleet_contract_violations": sup_report["contract_violations"],
        # Per-replica guard counters over each replica's whole service
        # window (serve.py replica mode): all must be 0.
        "fleet_replica_recompiles": [
            per_replica[i].get("recompiles") for i in range(n_replicas)
        ],
        "fleet_replica_host_transfers": [
            per_replica[i].get("host_transfers")
            for i in range(n_replicas)
        ],
        # Occupancy: who actually carried the window (routing skew).
        "fleet_per_replica_completed": [
            per_replica[i].get("completed") for i in range(n_replicas)
        ],
        "fleet_per_replica_dispatched": [
            rreport["per_replica_dispatched"].get(i, 0)
            for i in range(n_replicas)
        ],
        # Per-hop attribution (router queue / wire / replica / return)
        # from the cross-process trace propagation — p50/p99 per hop
        # over the window (docs/OBSERVABILITY.md "Trace propagation").
        "fleet_hops": fleet_hops,
    }
    lat_off = [
        r.latency_s
        for r in responses_off
        if r.ok and r.latency_s is not None
    ]
    if lat_off and dt_off:
        p50_on = record["fleet_p50_ms"]
        p50_off = nearest_rank_ms(lat_off, 0.50)
        record["fleet_p50_ms_notelemetry"] = p50_off
        record["fleet_p99_ms_notelemetry"] = nearest_rank_ms(lat_off, 0.99)
        record["fleet_ok_notelemetry"] = len(lat_off)
        if p50_off:
            record["fleet_telemetry_overhead_pct"] = round(
                100.0 * (p50_on - p50_off) / p50_off, 2
            )
    return record


def _measure_elasticity(shape: dict, corr_impl: str) -> dict:
    """Guarded elasticity row (docs/FLEET.md "Elasticity bench";
    ROADMAP item 3): the SLO-driven autoscaler driven by the
    deterministic low→high→cooldown traffic step
    (raft_ncup_tpu/traffic.py StepTraffic.step — the same schedule the
    acceptance tests replay) on a REAL fleet: serve.py replica
    processes, wire sockets, spawn-time compile warmup, the exit-75
    drain contract.

    Where the fleet row must measure SERVICE (any shed disqualifies
    it), this row must measure the MACHINERY. It answers the three
    elasticity questions with numbers flip_recommendations judges:

    - did the load step force a scale-up, and how long until the new
      capacity was READY (``elasticity_time_to_ready_s`` — measured
      spawn→READY, the same estimate shed hints are floored at)?
    - did the post-burst calm give capacity back
      (``elasticity_scale_downs``) with ZERO in-flight loss
      (``elasticity_losses`` — responses neither served nor honestly
      shed — must be 0; drain-contract violations disqualify the row)?
    - what did clients experience through both transitions (per-phase
      ok/shed split, overall p50/p99; sheds during the warmup window
      are honest backpressure but must carry a ``retry_after_s``
      floored above the default — ``elasticity_shed_eta_floored``)?

    The fleet starts at min_replicas=1 with max_replicas=2: the high
    phase MUST overload the single replica (its interval is calibrated
    to a fraction of the measured per-pair service time), and the
    cooldown phase plus a bounded settle window must let the autoscaler
    give the burst capacity back. BENCH_ELASTICITY_HIGH (default 18) /
    BENCH_ELASTICITY_LOW (default 4) size the phases,
    BENCH_ELASTICITY_GRACE_S (default 120) bounds the settle window,
    and BENCH_SKIP_ELASTICITY=1 turns the row off.
    """
    import numpy as np

    from raft_ncup_tpu.config import ServeConfig
    from raft_ncup_tpu.data.synthetic import SyntheticFlowDataset
    from raft_ncup_tpu.fleet import (
        FleetAutoscaler,
        FleetConfig,
        FleetRouter,
        ReplicaSupervisor,
    )
    from raft_ncup_tpu.observability import Telemetry
    from raft_ncup_tpu.serving import nearest_rank_ms
    from raft_ncup_tpu.traffic import StepTraffic

    H, W = shape["height"], shape["width"]
    iters = shape["iters"]
    low_n = knob_int("BENCH_ELASTICITY_LOW")
    high_n = knob_int("BENCH_ELASTICITY_HIGH")
    grace_s = knob_float("BENCH_ELASTICITY_GRACE_S")
    platform = os.environ.get("_BENCH_FORCE_PLATFORM") or "cpu"

    import tempfile

    base = tempfile.mkdtemp(prefix="bench_elasticity_")
    cfg = FleetConfig(
        base_dir=base,
        n_replicas=1,          # start at the floor: the step must EARN
        min_replicas=1,        # the second replica
        max_replicas=2,
        size_hw=(H, W),
        serve=ServeConfig(
            queue_capacity=max(8, high_n), batch_sizes=(1, 2),
            iter_levels=(iters,), recover_patience=2,
        ),
        stream=None,
        extra_args=(
            "--model", "raft_nc_dbl", "--corr_impl", corr_impl,
            "--platform", platform,
        ),
        snapshot_interval_s=0.5,
        # Tight admission so the high phase saturates one replica, and
        # reactive anti-flap bounds sized for a one-burst window (the
        # production defaults assume minutes-long burns).
        max_inflight_per_replica=3,
        scale_hysteresis_ticks=2,
        scale_cooldown_s=1.0,
        scale_tick_s=0.25,
    )
    tel = Telemetry()
    sup = ReplicaSupervisor(cfg, telemetry=tel)
    ds = SyntheticFlowDataset((H, W), length=4, seed=131, style="rigid")
    try:
        sup.start()  # one replica, warm
        router = FleetRouter(cfg, sup, telemetry=tel)
        sc = FleetAutoscaler(cfg, sup, router, telemetry=tel)

        # Calibrate the step against THIS host's service time: the high
        # phase arrives 4x faster than one replica serves, the low
        # phases comfortably slower — the rate step is the scenario, the
        # absolute rate is the host's.
        t0 = time.perf_counter()
        for i in range(2):
            s = ds.sample(i)
            router.submit(
                np.asarray(s["image1"], np.float32),
                np.asarray(s["image2"], np.float32),
            ).result(timeout=120.0)
        per_pair = (time.perf_counter() - t0) / 2.0
        high_interval = max(0.001, per_pair / 2.0)
        traffic = StepTraffic.step(
            (H, W), low_n=low_n, high_n=high_n,
            low_interval_s=max(0.05, per_pair * 1.5),
            high_interval_s=high_interval,
            seed=131, style="rigid",
        )
        items = list(traffic.schedule())

        # Replay the schedule with the control loop interleaved on its
        # own cadence (manual ticks — deterministic accounting, no
        # background thread racing the submit loop). The cadence must
        # land several ticks INSIDE the high phase — hysteresis needs
        # consecutive pressure observations, and a burst shorter than
        # one tick is invisible to the loop by design.
        tick_every = min(
            cfg.scale_tick_s, max(0.02, high_n * high_interval / 8.0)
        )
        handles = []
        last_tick = -tick_every
        t0 = time.perf_counter()
        for item in items:
            while True:
                now = time.perf_counter() - t0
                if now - last_tick >= tick_every:
                    sc.tick()
                    last_tick = now
                if now >= item.due_s:
                    break
                time.sleep(min(0.01, item.due_s - now))
            handles.append(router.submit(item.image1, item.image2))
        # Settle: keep ticking until every initiated topology change
        # resolved AND the burst capacity was given back (or the grace
        # window expires — the record then shows the open cycle).
        deadline = time.perf_counter() + grace_s
        while time.perf_counter() < deadline:
            sc.tick()
            rep = sc.report()
            settled = (
                rep["scale_ups"]
                == rep["scale_ups_completed"] + rep["failed_scale_ups"]
                and rep["scale_downs"] >= rep["scale_ups_completed"]
                and router.pending_count() == 0
            )
            if settled:
                break
            time.sleep(tick_every)
        responses = [h.result(timeout=60.0) for h in handles]
        dt = time.perf_counter() - t0
        sc.stop()  # clears the published ETA
        rreport = router.report()
        screport = sc.report()
        router.drain()
    finally:
        reports = sup.stop()

    lat = [
        r.latency_s for r in responses if r.ok and r.latency_s is not None
    ]
    if not lat:
        raise RuntimeError(
            f"no ok responses in elasticity window: {rreport['stats']}"
        )
    statuses: dict = {}
    for r in responses:
        statuses[r.status] = statuses.get(r.status, 0) + 1
    per_phase = {p.name: {"ok": 0, "shed": 0, "other": 0}
                 for p in traffic.phases}
    for item, r in zip(items, responses):
        bucket = per_phase[item.phase]
        key = r.status if r.status in ("ok", "shed") else "other"
        bucket[key] += 1
    sup_report = sup.report()
    # Guard counters from EVERY replica that served the window: retired
    # (scaled-down) replicas report via their drain's final JSON line,
    # survivors via teardown — a leaking replica poisons the row either
    # way.
    served = sorted(
        [(h.index, h.final_report or {}) for h in sup.retired]
        + [(i, (r or {}).get("report") or {}) for i, r in reports.items()]
    )
    return {
        "elasticity_requests": len(items),
        "elasticity_ok": len(lat),
        "elasticity_shed": statuses.get("shed", 0),
        "elasticity_errors": statuses.get("error", 0),
        "elasticity_timeouts": statuses.get("timeout", 0),
        # A loss is any response neither served nor honestly shed:
        # errors, timeouts, rejections, router-drain strandings.
        "elasticity_losses": sum(
            1 for r in responses if r.status not in ("ok", "shed")
        ),
        "elasticity_p50_ms": nearest_rank_ms(lat, 0.50),
        "elasticity_p99_ms": nearest_rank_ms(lat, 0.99),
        "elasticity_window_s": round(dt, 2),
        "elasticity_per_phase": per_phase,
        "elasticity_scale_ups": screport["scale_ups"],
        "elasticity_scale_ups_completed": screport["scale_ups_completed"],
        "elasticity_scale_downs": screport["scale_downs"],
        "elasticity_failed_scale_ups": screport["failed_scale_ups"],
        "elasticity_breaker_open": screport["breaker_open"],
        "elasticity_time_to_ready_s": screport["time_to_ready_s"],
        "elasticity_time_to_ready_observed": (
            screport["time_to_ready_observed"]
        ),
        "elasticity_ticks": screport["ticks"],
        # Backpressure honesty: sheds whose hint was floored ABOVE the
        # 250ms default — during a cold scale-up that floor is the
        # autoscaler's published time-to-READY estimate.
        "elasticity_shed_eta_floored": sum(
            1 for r in responses
            if r.status == "shed"
            and (r.retry_after_s or 0.0) > cfg.default_retry_after_s
        ),
        "elasticity_failovers": rreport["stats"].get("failovers", 0),
        "elasticity_deaths": sup_report["deaths"],
        "elasticity_restarts": sup_report["restarts"],
        "elasticity_contract_violations": (
            sup_report["contract_violations"]
        ),
        "elasticity_replica_recompiles": [
            rep.get("recompiles") for _, rep in served
        ],
        "elasticity_replica_host_transfers": [
            rep.get("host_transfers") for _, rep in served
        ],
        "elasticity_interval_high_ms": round(
            traffic.phases[1].interval_s * 1e3, 1
        ),
        "elasticity_interval_low_ms": round(
            traffic.phases[0].interval_s * 1e3, 1
        ),
    }


def _measure_highres(variables: dict, precision: str = "f32") -> dict:
    """Guarded 1080p-class throughput row, spatially sharded whenever
    the visible mesh has >1 device (docs/SHARDING.md; ROADMAP item 4).

    The workload is the flagship onthefly-corr test-mode forward at
    1088x1920 — the camera-resolution configuration whose O(HW) lookup
    working set spatial sharding exists to split. Iteration count is
    honest per platform: 32 (the Sintel eval setting) on an
    accelerator, reduced (env ``BENCH_HIGHRES_ITERS``, default 2) on
    CPU where a 32-iter 1080p forward runs for minutes.

    Mesh: env ``BENCH_MESH`` ("data,spatial", set by ``--mesh``) wins;
    otherwise (1, n_devices) with the spatial size walked down until it
    divides the 1/8-res feature height. One device = unsharded — the
    row still records, clearly fingerprinted ``nomesh``.

    Sharding provenance: ``highres_mesh`` / ``highres_devices`` plus
    the ``collective_stats`` fingerprint of the compiled program
    (``highres_collectives`` / ``highres_collective_bytes`` — 0/0 when
    unsharded, the partitioner's halo exchanges + fmap2 all-gathers
    otherwise), and ``highres_analysis_temp_gib`` is the PER-DEVICE
    compile-time footprint, which should drop roughly with the shard
    count vs the unsharded comparison window.

    Guards: the timed reps run under ``RecompileWatchdog`` +
    ``forbid_host_transfers`` — ``highres_recompiles`` /
    ``highres_host_transfers`` must be 0 (the per-rep sync is one
    sanctioned ``jax.device_get`` of a scalar). When sharded, an
    unsharded comparison window (same iters/reps; skip with
    ``BENCH_HIGHRES_COMPARE=0``) records
    ``highres_pairs_per_sec_unsharded`` so
    ``flip_recommendations`` can judge the mesh default from data; its
    guard counters fold into the same two fields (a leak in either
    window invalidates the comparison).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from raft_ncup_tpu.analysis.guards import (
        GuardStats,
        RecompileWatchdog,
        forbid_host_transfers,
    )
    from raft_ncup_tpu.config import flagship_config
    from raft_ncup_tpu.models.raft import get_model
    from raft_ncup_tpu.parallel.mesh import (
        collective_stats,
        make_mesh,
        mesh_fingerprint,
    )
    from raft_ncup_tpu.parallel.step import make_eval_step

    platform = jax.devices()[0].platform
    H, W = (
        int(x)
        for x in knob_str("BENCH_HIGHRES_SIZE").split(",")
    )
    iters = knob_int(
        "BENCH_HIGHRES_ITERS", default="32" if platform != "cpu" else "2"
    )
    reps = knob_int(
        "BENCH_HIGHRES_REPS", default="3" if platform != "cpu" else "2"
    )
    strict = knob_flag("BENCH_STRICT_GUARDS")

    devices = jax.devices()
    spec = _parse_mesh_env()
    if spec is not None and (1 % spec[0] or (H // 8) % spec[1]):
        # The workload is batch 1 at this H: a data axis > 1 or a
        # spatial size that does not divide H//8 cannot shard it —
        # fall back to the auto mesh rather than silently losing the
        # row to a jit sharding error.
        print(
            f"BENCH_MESH {spec}: incompatible with the 1x{H}x{W} "
            f"highres workload (batch 1, H//8 = {H // 8}); using the "
            "auto-derived mesh instead",
            file=sys.stderr,
        )
        spec = None
    if spec is not None:
        data, spatial = spec
    else:
        data, spatial = 1, len(devices)
        while spatial > 1 and (H // 8) % spatial:
            spatial -= 1
    n_dev = data * spatial
    mesh = (
        make_mesh(data=data, spatial=spatial, devices=devices[:n_dev])
        if n_dev > 1
        else None
    )

    model = get_model(
        flagship_config(
            dataset="sintel", corr_impl="onthefly", precision=precision
        )
    )

    def window(mesh_):
        step = make_eval_step(model, iters=iters, mesh=mesh_)
        img = jax.ShapeDtypeStruct((1, H, W, 3), jnp.float32)
        t0 = time.perf_counter()
        compiled = step.lower(variables, img, img).compile()
        compile_s = time.perf_counter() - t0
        mem = compiled.memory_analysis()
        try:
            coll = collective_stats(compiled.as_text())
        except Exception as e:  # pragma: no cover - backend-specific
            print(f"collective_stats unavailable: {e}", file=sys.stderr)
            coll = {"collectives": None, "collective_bytes": None}
        rng = np.random.default_rng(7)
        img1 = jnp.asarray(rng.uniform(0, 255, (1, H, W, 3)), jnp.float32)
        img2 = jnp.asarray(rng.uniform(0, 255, (1, H, W, 3)), jnp.float32)
        # Warm rep outside the guards: also compiles the tiny scalar-
        # slice sync program so the timed window sees zero compiles.
        out = compiled(variables, img1, img2)
        jax.device_get(out[1][0, 0, 0, 0])
        stats = GuardStats()
        rep_s = []
        with RecompileWatchdog() as wd, forbid_host_transfers(
            stats, raise_on_violation=strict
        ):
            for _ in range(max(1, reps)):
                t0 = time.perf_counter()
                out = compiled(variables, img1, img2)
                # The honest sync (axon's block_until_ready returns
                # early) via the one sanctioned explicit device_get.
                jax.device_get(out[1][0, 0, 0, 0])
                rep_s.append(time.perf_counter() - t0)
        rep_s.sort()
        median = rep_s[len(rep_s) // 2]
        return {
            "pairs_per_sec": round(1.0 / median, 4) if median else 0.0,
            "rep_ms": [round(t * 1e3, 1) for t in rep_s],
            "compile_s": round(compile_s, 1),
            "temp_gib": round(int(mem.temp_size_in_bytes) / 2**30, 3),
            "recompiles": wd.count,
            "host_transfers": stats.host_transfers,
            **coll,
        }

    main_w = window(mesh)
    row = {
        "highres_pairs_per_sec": main_w["pairs_per_sec"],
        "highres_rep_ms": main_w["rep_ms"],
        "highres_shape": f"1x{H}x{W}",
        "highres_iters": iters,
        "highres_compile_s": main_w["compile_s"],
        "highres_mesh": mesh_fingerprint(mesh),
        "highres_devices": n_dev,
        "highres_analysis_temp_gib": main_w["temp_gib"],
        "highres_collectives": main_w["collectives"],
        "highres_collective_bytes": main_w["collective_bytes"],
        "highres_recompiles": main_w["recompiles"],
        "highres_host_transfers": main_w["host_transfers"],
    }
    if mesh is not None and knob_enabled("BENCH_HIGHRES_COMPARE"):
        ref = window(None)
        row["highres_pairs_per_sec_unsharded"] = ref["pairs_per_sec"]
        row["highres_analysis_temp_gib_unsharded"] = ref["temp_gib"]
        row["highres_recompiles"] += ref["recompiles"]
        row["highres_host_transfers"] += ref["host_transfers"]
    return row


def _measure_uhd(variables: dict, precision: str = "f32") -> dict:
    """Guarded UHD (4K) throughput row: the flagship test-mode forward
    at 2176x3840 — the shape the banded Pallas corr tier
    (ops/corr_pallas.py; docs/PERF.md "Banded dispatch") broke the
    correlation memory wall for.

    Honest per platform: on a TPU-class backend the row runs
    ``corr_impl='pallas'`` (resident + banded kernel tiers; the
    trace-time tier tally lands in ``uhd_corr_dispatch``) at the Sintel
    eval iteration count; on CPU it runs the XLA onthefly fallback at
    reduced iters (``BENCH_UHD_ITERS``, default 1 — a 4K interpret-mode
    kernel window is not a measurement) and the row says so
    (``uhd_corr_impl``/``uhd_platform``) so ``flip_recommendations``
    stages it rather than judging it. Overrides: ``BENCH_UHD_SIZE``
    ("H,W"), ``BENCH_UHD_CORR``, ``BENCH_UHD_REPS``.

    The correlation tuning knobs behind the window — onthefly
    ``row_chunk`` (``RAFT_NCUP_CORR_ROW_CHUNK``), Pallas query block /
    band rows — are recorded (``uhd_corr_row_chunk`` /
    ``uhd_corr_query_block`` / ``uhd_corr_band_rows``), the same values
    the cost ledger stamps into the executable's meta.

    Guards: timed reps under ``RecompileWatchdog`` +
    ``forbid_host_transfers`` — ``uhd_recompiles`` /
    ``uhd_host_transfers`` must be 0 (per-rep sync is one sanctioned
    scalar ``jax.device_get``).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from raft_ncup_tpu.analysis.guards import (
        GuardStats,
        RecompileWatchdog,
        forbid_host_transfers,
    )
    from raft_ncup_tpu.config import flagship_config
    from raft_ncup_tpu.models.raft import get_model
    from raft_ncup_tpu.ops import corr_pallas as cpk
    from raft_ncup_tpu.ops.corr import corr_tuning_meta
    from raft_ncup_tpu.parallel.step import make_eval_step

    platform = jax.devices()[0].platform
    on_accel = platform != "cpu"
    H, W = (
        int(x)
        for x in knob_str("BENCH_UHD_SIZE").split(",")
    )
    iters = knob_int("BENCH_UHD_ITERS", default="32" if on_accel else "1")
    reps = knob_int("BENCH_UHD_REPS", default="3" if on_accel else "2")
    corr_impl = knob_str(
        "BENCH_UHD_CORR", default="pallas" if on_accel else "onthefly"
    )
    strict = knob_flag("BENCH_STRICT_GUARDS")

    model = get_model(
        flagship_config(
            dataset="sintel", corr_impl=corr_impl, precision=precision
        )
    )
    step = make_eval_step(model, iters=iters, mesh=None)
    img = jax.ShapeDtypeStruct((1, H, W, 3), jnp.float32)
    cpk.reset_dispatch_counts()
    t0 = time.perf_counter()
    compiled = step.lower(variables, img, img).compile()
    compile_s = time.perf_counter() - t0
    dispatch = cpk.dispatch_counts() if corr_impl == "pallas" else None
    mem = compiled.memory_analysis()

    rng = np.random.default_rng(11)
    img1 = jnp.asarray(rng.uniform(0, 255, (1, H, W, 3)), jnp.float32)
    img2 = jnp.asarray(rng.uniform(0, 255, (1, H, W, 3)), jnp.float32)
    # Warm rep outside the guards: also compiles the tiny scalar-slice
    # sync program so the timed window sees zero compiles.
    out = compiled(variables, img1, img2)
    jax.device_get(out[1][0, 0, 0, 0])
    stats = GuardStats()
    rep_s = []
    with RecompileWatchdog() as wd, forbid_host_transfers(
        stats, raise_on_violation=strict
    ):
        for _ in range(max(1, reps)):
            t0 = time.perf_counter()
            out = compiled(variables, img1, img2)
            jax.device_get(out[1][0, 0, 0, 0])
            rep_s.append(time.perf_counter() - t0)
    rep_s.sort()
    median = rep_s[len(rep_s) // 2]
    tuning = corr_tuning_meta()
    row = {
        "uhd_pairs_per_sec": round(1.0 / median, 4) if median else 0.0,
        "uhd_rep_ms": [round(t * 1e3, 1) for t in rep_s],
        "uhd_shape": f"1x{H}x{W}",
        "uhd_iters": iters,
        "uhd_corr_impl": corr_impl,
        "uhd_platform": platform,
        "uhd_compile_s": round(compile_s, 1),
        "uhd_analysis_temp_gib": round(
            int(mem.temp_size_in_bytes) / 2**30, 3
        ),
        "uhd_corr_row_chunk": tuning["corr_row_chunk"],
        "uhd_corr_query_block": tuning.get("corr_query_block"),
        "uhd_corr_band_rows": tuning.get("corr_band_rows"),
        "uhd_recompiles": wd.count,
        "uhd_host_transfers": stats.host_transfers,
    }
    if dispatch is not None:
        row["uhd_corr_dispatch"] = dispatch
    return row


def _measure_pipeline(variables: dict) -> dict:
    """Guarded iteration-pipeline streaming row (docs/SHARDING.md
    "Pipeline axis"; inference/pipe_schedule.py): micro-batches
    streamed through S scan segments on an S-stage ``pipe`` mesh,
    measured over a full warm stream (M micro-batches, M+S-1 ticks,
    fill and flush INCLUDED — the honest steady-state figure a serving
    deployment would see, not a cherry-picked middle tick).

    Segment count: ``BENCH_PIPELINE_SEGMENTS`` wins, else the largest
    of {4, 2} that the visible device count admits, else 1 — on a
    single-device host the row records the monolithic delegation path,
    clearly fingerprinted ``nomesh``/``pipeline_segments=1``. On CPU
    the virtual pipeline stages share one host, so the S× throughput
    claim is NOT measurable here (``pipeline_platform`` says so and
    flip_recommendations stages rather than judges); what the CPU row
    DOES pin is the guard-clean steady state and the
    collective-permute handoff fingerprint.

    Provenance: ``pipeline_mesh``/``pipeline_segments``/
    ``pipeline_micro_batches``; the tick executable's per-segment cost
    split from the ledger (``pipeline_flops_per_segment`` /
    ``pipeline_bytes_per_segment`` — inference/costs.py); the
    ``collective_stats`` per-op breakout of the WARMED tick
    (``pipeline_collective_permutes`` — the carry-handoff traffic,
    read at zero compile cost via ``tick_text``). When pipelined, a
    monolithic comparison window (same pairs/iters, segments=1; skip
    with ``BENCH_PIPELINE_COMPARE=0``) records
    ``pipeline_pairs_per_sec_monolithic`` so flip_recommendations can
    judge the pipeline from data; its guard counters fold into the
    same two fields. Overrides: ``BENCH_PIPELINE_SIZE`` ("H,W"),
    ``BENCH_PIPELINE_ITERS`` (quantized down to a multiple of S),
    ``BENCH_PIPELINE_BATCHES``.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from raft_ncup_tpu.analysis.guards import (
        GuardStats,
        RecompileWatchdog,
        forbid_host_transfers,
    )
    from raft_ncup_tpu.config import flagship_config
    from raft_ncup_tpu.inference.costs import get_cost_ledger
    from raft_ncup_tpu.inference.pipe_schedule import PipelinedForward
    from raft_ncup_tpu.models.raft import get_model
    from raft_ncup_tpu.parallel.mesh import (
        collective_stats,
        mesh_fingerprint,
    )

    platform = jax.devices()[0].platform
    n_dev = len(jax.devices())
    env_segments = knob_positive_int("BENCH_PIPELINE_SEGMENTS")
    if env_segments:
        segments = env_segments
    else:
        segments = next((s for s in (4, 2) if s <= n_dev), 1)
    H, W = (
        int(x)
        for x in knob_str("BENCH_PIPELINE_SIZE").split(",")
    )
    iters = knob_int(
        "BENCH_PIPELINE_ITERS", default="32" if platform != "cpu" else "4"
    )
    # Budgets quantize to segment boundaries (serving/budget.py); so
    # does the bench knob — down, never up (honest about work done).
    iters = max(segments, iters - iters % segments)
    micro = knob_int("BENCH_PIPELINE_BATCHES", default=str(2 * segments))
    strict = knob_flag("BENCH_STRICT_GUARDS")

    model = get_model(flagship_config(dataset="sintel", corr_impl="onthefly"))
    rng = np.random.default_rng(11)
    pairs = [
        (
            jnp.asarray(rng.uniform(0, 255, (1, H, W, 3)), jnp.float32),
            jnp.asarray(rng.uniform(0, 255, (1, H, W, 3)), jnp.float32),
        )
        for _ in range(micro)
    ]

    def window(segs):
        pf = PipelinedForward(model, variables, segments=segs)
        # Warm stream outside the guards: compiles encode + tick (and
        # the tiny scalar-slice sync program).
        t0 = time.perf_counter()
        outs = pf.forward_many(pairs, iters)
        jax.device_get(outs[-1][1][0, 0, 0, 0])
        warm_s = time.perf_counter() - t0
        stats = GuardStats()
        with RecompileWatchdog() as wd, forbid_host_transfers(
            stats, raise_on_violation=strict
        ):
            t0 = time.perf_counter()
            outs = pf.forward_many(pairs, iters)
            # The one sanctioned explicit device_get: the honest sync.
            jax.device_get(outs[-1][1][0, 0, 0, 0])
            elapsed = time.perf_counter() - t0
        return pf, {
            "pairs_per_sec": round(micro / elapsed, 4) if elapsed else 0.0,
            "warm_s": round(warm_s, 1),
            "recompiles": wd.count,
            "host_transfers": stats.host_transfers,
        }

    pf, main_w = window(segments)
    row = {
        "pipeline_pairs_per_sec": main_w["pairs_per_sec"],
        "pipeline_segments": pf.segments,
        "pipeline_micro_batches": micro,
        "pipeline_shape": f"1x{H}x{W}",
        "pipeline_iters": iters,
        "pipeline_platform": platform,
        "pipeline_mesh": mesh_fingerprint(pf.mesh),
        "pipeline_warm_s": main_w["warm_s"],
        "pipeline_recompiles": main_w["recompiles"],
        "pipeline_host_transfers": main_w["host_transfers"],
    }
    hlo = pf.tick_text((1, H, W, 3), iters)
    if hlo is not None:
        cp = collective_stats(hlo)["by_op"]["collective-permute"]
        row["pipeline_collective_permutes"] = cp["count"]
        row["pipeline_collective_permute_bytes"] = cp["bytes"]
    led = get_cost_ledger().lookup(kind="pipe_tick", segments=segments)
    if led is not None:
        row["pipeline_tick_flops"] = led.get("flops")
        row["pipeline_flops_per_segment"] = led.get("flops_per_segment")
        row["pipeline_bytes_per_segment"] = led.get("bytes_per_segment")
        row["pipeline_tick_compile_ms"] = led.get("compile_ms")
    if segments > 1 and knob_enabled("BENCH_PIPELINE_COMPARE"):
        _, ref = window(1)
        row["pipeline_pairs_per_sec_monolithic"] = ref["pairs_per_sec"]
        row["pipeline_recompiles"] += ref["recompiles"]
        row["pipeline_host_transfers"] += ref["host_transfers"]
    return row


def _measure_earlyexit(variables: dict) -> dict:
    """Adaptive-compute row (docs/PERF.md "Early exit"): the in-graph
    convergence-detection forward vs its own full-budget twin over a
    mixed-resolution zipf request stream.

    The stream is :class:`~raft_ncup_tpu.traffic.MixedResolutionTraffic`
    over three small sizes (batch 1 — the serving admission shape), so
    the recorded speedup reflects HETEROGENEOUS per-sample convergence
    across a realistic size mix, not one shape's behavior. Both windows
    replay the SAME frames through the SAME weights; the only variable
    is detection, so the throughput delta is the measured FLOP cut and
    ``earlyexit_epe_vs_full`` is the measured quality price — judged
    against the pinned ``EARLYEXIT_EPE_BUDGET`` (precision/policy.py)
    by flip_recommendations before any speedup may be recommended. The
    FLOP cut is backend-honest (fewer while_loop trips is fewer FLOPs
    everywhere), so the CPU verdict is real, unlike the pipeline row's
    S× claim.

    Guards: both windows run under the recompile watchdog and the
    implicit-transfer tripwire — ``earlyexit_recompiles`` /
    ``earlyexit_host_transfers`` (both windows folded) must be 0, the
    proof that detection lives in-graph: no host pull ever inspects the
    convergence mask, and the executable set compiled at warm time (one
    per (shape, detection) — the tolerance is baked into the compiled
    loop condition) is the set the window ran. Warmup compiles both
    variants per shape outside the guards; result pulls (EPE inputs,
    exec counts) happen after the guard scopes close.

    Knobs: ``BENCH_EARLYEXIT_TOL`` (detection threshold, mean |flow
    delta| in LOW-RES px — the default is tuned so the untrained bench
    weights split, some lanes exiting early and some running out the
    budget), ``BENCH_EARLYEXIT_ITERS`` (the budget both windows share),
    ``BENCH_EARLYEXIT_REQUESTS`` (stream length),
    ``BENCH_SKIP_EARLYEXIT`` (skip the row).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from raft_ncup_tpu.analysis.guards import (
        GuardStats,
        RecompileWatchdog,
        forbid_host_transfers,
    )
    from raft_ncup_tpu.config import flagship_config
    from raft_ncup_tpu.inference.pipeline import ShapeCachedForward
    from raft_ncup_tpu.models.raft import get_model
    from raft_ncup_tpu.precision import EARLYEXIT_EPE_BUDGET
    from raft_ncup_tpu.traffic import MixedResolutionTraffic

    platform = jax.devices()[0].platform
    tol = knob_float("BENCH_EARLYEXIT_TOL")
    iters = knob_int("BENCH_EARLYEXIT_ITERS")
    n = knob_int("BENCH_EARLYEXIT_REQUESTS")
    strict = knob_flag("BENCH_STRICT_GUARDS")
    sizes = [(96, 128), (64, 96), (128, 160)]

    traffic = MixedResolutionTraffic(sizes, n, seed=17, style="smooth")
    items = [
        (
            jnp.asarray(item.image1[None], jnp.float32),
            jnp.asarray(item.image2[None], jnp.float32),
        )
        for item in traffic.schedule()
    ]

    model = get_model(flagship_config(dataset="sintel", corr_impl="onthefly"))
    fwd = ShapeCachedForward(model, variables)

    # Warm both variants for every distinct shape OUTSIDE the guards:
    # after this, the window's executable set is closed.
    warmed = set()
    t0 = time.perf_counter()
    for i1, i2 in items:
        if i1.shape in warmed:
            continue
        warmed.add(i1.shape)
        out = fwd.forward_device(i1, i2, iters, early_exit_tol=tol)
        jax.device_get(out[1][0, 0, 0, 0])
        out = fwd.forward_device(i1, i2, iters)
        jax.device_get(out[1][0, 0, 0, 0])
    warm_s = time.perf_counter() - t0

    def window(ee_tol):
        outs = []
        stats = GuardStats()
        with RecompileWatchdog() as wd, forbid_host_transfers(
            stats, raise_on_violation=strict
        ):
            t0 = time.perf_counter()
            for i1, i2 in items:
                outs.append(
                    fwd.forward_device(i1, i2, iters, early_exit_tol=ee_tol)
                )
            # The one sanctioned explicit device_get: the honest sync.
            # On the single-stream backends dispatch is in-order, so the
            # last result's scalar fences the whole window.
            jax.device_get(outs[-1][1][0, 0, 0, 0])
            elapsed = time.perf_counter() - t0
        return outs, {
            "pairs_per_sec": (
                round(len(items) / elapsed, 4) if elapsed else 0.0
            ),
            "recompiles": wd.count,
            "host_transfers": stats.host_transfers,
        }

    ee_outs, ee_w = window(tol)
    full_outs, full_w = window(None)

    # Result pulls AFTER the guard scopes: explicit, off the clock.
    exec_iters = np.concatenate(
        [np.asarray(jax.device_get(o[2])) for o in ee_outs]
    ).astype(np.int64)
    epes = []
    for ee, full in zip(ee_outs, full_outs):
        d = np.asarray(jax.device_get(ee[1])) - np.asarray(
            jax.device_get(full[1])
        )
        epes.append(float(np.sqrt((d ** 2).sum(-1)).mean()))
    ex = np.sort(exec_iters)

    def nearest(p):  # classical nearest-rank (serving.nearest_rank_ms)
        return int(ex[max(0, min(len(ex), int(np.ceil(p * len(ex)))) - 1)])
    return {
        "earlyexit_pairs_per_sec": ee_w["pairs_per_sec"],
        "earlyexit_pairs_per_sec_fullbudget": full_w["pairs_per_sec"],
        "earlyexit_epe_vs_full": round(float(np.mean(epes)), 4),
        "earlyexit_epe_budget": EARLYEXIT_EPE_BUDGET,
        "earlyexit_tol": tol,
        "earlyexit_iters_budgeted": iters,
        "earlyexit_iters_executed_mean": round(float(ex.mean()), 3),
        "earlyexit_iters_executed_p50": nearest(0.50),
        "earlyexit_iters_executed_p99": nearest(0.99),
        "earlyexit_requests": len(items),
        "earlyexit_size_mix": traffic.size_counts(),
        "earlyexit_platform": platform,
        "earlyexit_warm_s": round(warm_s, 1),
        "earlyexit_recompiles": ee_w["recompiles"] + full_w["recompiles"],
        "earlyexit_host_transfers": (
            ee_w["host_transfers"] + full_w["host_transfers"]
        ),
    }


def _measure_checkpoint(handles: dict) -> dict:
    """Time one full-train-state orbax save (+commit wait) and restore at
    the bench shape — the resilience numbers (docs/RESILIENCE.md):
    ``ckpt_save_ms`` bounds what a preemption grace window must absorb
    (preemption saves exactly one checkpoint), and ``ckpt_restore_ms`` is
    the fixed part of kill/resume overhead (the variable part — process
    start + jit compile — is amortized by the persistent compilation
    cache). Runs AFTER the train-loop row on a throwaway directory, so it
    cannot perturb `train_loop_pairs_per_sec`."""
    import shutil
    import tempfile

    from raft_ncup_tpu.training.checkpoint import CheckpointManager

    state = handles["state"]
    tmp = tempfile.mkdtemp(prefix="bench_ckpt_")
    mgr = None
    try:
        mgr = CheckpointManager(tmp, max_to_keep=1)
        t0 = time.perf_counter()
        mgr.save(state)  # synchronous: staging + commit
        save_ms = (time.perf_counter() - t0) * 1000.0
        t0 = time.perf_counter()
        mgr.restore(state)
        restore_ms = (time.perf_counter() - t0) * 1000.0
    finally:
        # Close before rmtree, and on the failure path too — a leaked
        # manager keeps async-save threads alive under a deleted dir.
        if mgr is not None:
            try:
                mgr.close()
            except Exception as e:
                print(f"checkpoint bench close failed: {e}", file=sys.stderr)
        shutil.rmtree(tmp, ignore_errors=True)
    return {
        "ckpt_save_ms": round(save_ms, 1),
        "ckpt_restore_ms": round(restore_ms, 1),
    }


def _parse_json_tail(stdout: str, key: str = "value"):
    for line in reversed((stdout or "").strip().splitlines()):
        try:
            out = json.loads(line)
            if isinstance(out, dict) and key in out:
                return out
        except ValueError:
            continue
    return None


def _val_child_main() -> None:
    """Forced-CPU val-row child: measures the eval-pipeline windows with
    an XLA host pool that leaves a core for the input pipeline (the
    parent set ``--xla_cpu_multi_thread_eigen=false``) and prints the
    ``val_*`` fields as one JSON line."""
    import jax

    from raft_ncup_tpu.utils.runtime import (
        enable_compilation_cache,
        force_platform,
    )

    force_platform("cpu")
    enable_compilation_cache()

    from raft_ncup_tpu.config import flagship_config
    from raft_ncup_tpu.models.raft import get_model

    shape = json.loads(os.environ["_BENCH_SHAPE"])
    corr_impl = knob_str("BENCH_CORR_IMPL")
    precision = os.environ.get("_BENCH_PRECISION", "f32")
    model = get_model(
        flagship_config(
            dataset="sintel", mixed_precision=False, corr_impl=corr_impl
        )
    )
    variables = model.init(
        jax.random.PRNGKey(0), (1, shape["height"], shape["width"], 3)
    )
    _emit(
        _measure_val_loop(
            shape, False, corr_impl, variables, precision=precision
        )
    )


def _run_val_child(
    shape: dict, corr_impl: str, timeout_s: float, precision: str = "f32"
):
    """Run the val row in a sub-child with the serving thread config
    (one host core reserved for the input pipeline). Returns the val_*
    fields dict, or None on failure/timeout. ``precision`` selects the
    policy preset the child measures under (the bf16 val row uses the
    SAME sub-child configuration as the f32 one, so the two rows differ
    only by policy)."""
    if timeout_s < 45:
        return None
    from raft_ncup_tpu.utils.backend_probe import run_watchdogged

    env = dict(os.environ)
    env.pop(_CHILD_ENV, None)
    env[_VAL_CHILD_ENV] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    env["_BENCH_SHAPE"] = json.dumps(shape)
    env["BENCH_CORR_IMPL"] = corr_impl
    env["_BENCH_PRECISION"] = precision
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_cpu_multi_thread_eigen=false"
    ).strip()
    res = run_watchdogged(
        [sys.executable, os.path.abspath(__file__)],
        timeout_s,
        env=env,
        cwd=_REPO,
    )
    out = _parse_json_tail(res.stdout, key="val_pairs_per_sec")
    if out is None and not res.timed_out:
        print(
            f"val sub-child failed rc={res.returncode}:\n" + res.tail(8),
            file=sys.stderr,
        )
    return out


def _run_child(env_overrides: dict, shape: dict, timeout_s: float):
    """Run the measurement in a child; returns ``(record_or_None,
    crashed)`` — ``crashed`` is True only for a nonzero exit, NOT for a
    watchdog timeout (the cache-wipe retry must not trigger on timeouts:
    a partially-warm cache is exactly what makes the retry viable).

    A child killed by the watchdog can still yield a result: the last JSON
    line it managed to print is harvested from the drained pipe (Popen
    path — subprocess.run's TimeoutExpired discards partial output)."""
    from raft_ncup_tpu.utils.backend_probe import run_watchdogged

    env = dict(os.environ)
    env.update(env_overrides)
    env[_CHILD_ENV] = "1"
    env["_BENCH_SHAPE"] = json.dumps(shape)
    env["_BENCH_CHILD_BUDGET_S"] = str(timeout_s)
    res = run_watchdogged(
        [sys.executable, os.path.abspath(__file__)],
        timeout_s,
        env=env,
        cwd=_REPO,
    )
    if res.timed_out:
        print(f"bench attempt timed out after {timeout_s:.0f}s", file=sys.stderr)
    out = _parse_json_tail(res.stdout)
    if out:
        return out, False
    if not res.timed_out:
        print(
            f"bench attempt failed rc={res.returncode}:\n" + res.tail(8),
            file=sys.stderr,
        )
    return None, (not res.timed_out and res.returncode != 0)


def main() -> None:
    if os.environ.get(_VAL_CHILD_ENV) == "1":
        _val_child_main()
        return
    if os.environ.get(_CHILD_ENV) == "1":
        _child_main()
        return

    # --trace_dir DIR: bank a jax.profiler device trace of the primary
    # measurement's timed reps (ROADMAP: first hardware contact should
    # record where the time goes, not just how much). Children inherit
    # it via the environment; env BENCH_TRACE_DIR works identically.
    import argparse

    ap = argparse.ArgumentParser(add_help=False)
    ap.add_argument("--trace_dir", default=None)
    # --mesh DATA,SPATIAL (docs/SHARDING.md): pins the mesh the highres
    # row (and any mesh-aware row) runs on. Children inherit it via env
    # BENCH_MESH; on the CPU fallback the product also forces that many
    # virtual host devices so the sharded program can actually execute.
    ap.add_argument("--mesh", default=knob_raw("BENCH_MESH"))
    cli_args, _ = ap.parse_known_args()
    if cli_args.trace_dir:
        os.environ["BENCH_TRACE_DIR"] = os.path.abspath(cli_args.trace_dir)
    mesh_devices = 0
    if cli_args.mesh:
        os.environ["BENCH_MESH"] = cli_args.mesh
        spec = _parse_mesh_env()
        if spec is None:
            # A spec the parser rejects must not reach the children
            # either — they would each re-reject it, or worse.
            os.environ.pop("BENCH_MESH", None)
        else:
            mesh_devices = spec[0] * spec[1]

    t0 = time.monotonic()

    def remaining() -> float:
        return TOTAL_BUDGET_S - (time.monotonic() - t0)

    result = None
    # 1) Probe the inherited platform (axon TPU under the driver). The
    #    probe is the hang detector: jax.devices() blocking is the exact
    #    r02 failure mode. A fast transient init failure (the round-1
    #    mode) is retried inside probe_backend; a hang is terminal.
    from raft_ncup_tpu.utils.backend_probe import probe_backend

    pr = probe_backend(min(PROBE_TIMEOUT_S, remaining() - CPU_RESERVE_S))
    probe = pr.platform
    if pr.reason != "ok":
        print(f"backend probe {pr.reason}: {pr.detail}", file=sys.stderr)
    if probe and probe != "cpu":
        budget = min(TPU_TIMEOUT_CAP_S, remaining() - CPU_RESERVE_S)
        if budget > 60:
            result, _ = _run_child({}, FULL, budget)
        # Secondary rows, budget permitting: the alternative corr
        # implementations and the fused NConv kernel at the same shape
        # (VERDICT.md next-round #2/#3/#5 — the data that decides the
        # default kernels on hardware).
        if result:
            variants = [
                ("onthefly", {"BENCH_CORR_IMPL": "onthefly"}),
                ("pallas", {"BENCH_CORR_IMPL": "pallas"}),
                ("nconv_pallas", {"RAFT_NCUP_NCONV_IMPL": "pallas"}),
            ]
            for tag, env in variants:
                spare = remaining() - CPU_RESERVE_S / 2
                if spare < 150:
                    break
                r2, _ = _run_child(env, FULL, min(300.0, spare))
                if r2:
                    if r2.get("fused_ok") is False:
                        # The fused kernel fell back to XLA: the number is
                        # real but the label would lie (ADVICE r3).
                        result[f"pairs_per_sec_{tag}_FELL_BACK_TO_XLA"] = (
                            r2["value"]
                        )
                        continue
                    _maybe_record_baseline(r2)
                    result[f"pairs_per_sec_{tag}"] = r2["value"]
                    if r2.get("train_pairs_per_sec") is not None:
                        result[f"train_pairs_per_sec_{tag}"] = r2[
                            "train_pairs_per_sec"
                        ]
                    if r2.get("train_loop_pairs_per_sec") is not None:
                        result[f"train_loop_pairs_per_sec_{tag}"] = r2[
                            "train_loop_pairs_per_sec"
                        ]
                    # Partial-fusion annotations must ride along: a row
                    # whose kernel only fused at some call sites/levels is
                    # labeled-but-annotated, and dropping the annotation
                    # here would let flip_recommendations read a mostly-XLA
                    # number as a clean kernel win.
                    for ann in ("nconv_pallas_calls", "corr_pallas_levels"):
                        if ann in r2:
                            result[ann] = r2[ann]
    elif probe == "cpu":
        # Inherited platform is already CPU — go straight to the CPU path.
        pass
    else:
        print("inherited backend dead/hanging; skipping TPU attempt",
              file=sys.stderr)
    # 2) Guaranteed CPU fallback at a reduced shape: always yields a number
    #    (judge-verified ~85s on this image). A fast CRASH can be a
    #    poisoned XLA compilation cache (AOT machine-feature mismatch can
    #    SIGILL) — wipe it and retry once. A timeout must NOT wipe: the
    #    partially-warm cache is what makes the retry viable.
    if not result:
        cpu_env = {"JAX_PLATFORMS": "cpu", "_BENCH_FORCE_PLATFORM": "cpu"}
        if mesh_devices > 1:
            # A pinned multi-device mesh on the CPU fallback needs that
            # many virtual host devices before the child's jax init.
            cpu_env["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count={mesh_devices}"
            ).strip()
        result, crashed = _run_child(
            cpu_env, SMALL, max(60.0, min(CPU_RESERVE_S, remaining() - 10))
        )
        if not result and crashed:
            from raft_ncup_tpu.utils.runtime import (
                wipe_compilation_cache_for_retry,
            )

            if wipe_compilation_cache_for_retry(remaining() - 10):
                print("wiped XLA cache, retrying CPU bench cold",
                      file=sys.stderr)
                result, _ = _run_child(
                    cpu_env, SMALL, max(60.0, remaining() - 10)
                )
        elif not result:
            # Timed out: retry warm (the first attempt's compile work is
            # in the cache) if budget allows.
            if remaining() > 90:
                result, _ = _run_child(
                    cpu_env, SMALL, max(60.0, remaining() - 10)
                )
    # 3) Late second probe (VERDICT r3 #2): tunnel wedges can be
    #    transient. If the first probe failed but the CPU fallback left
    #    budget, ask the accelerator again — a real chip row supersedes
    #    the CPU liveness record.
    if pr.reason != "ok" and remaining() > 300:
        pr2 = probe_backend(min(75.0, remaining() - 200))
        if pr2.reason == "ok" and pr2.platform and pr2.platform != "cpu":
            print("late probe found a live accelerator; re-benching",
                  file=sys.stderr)
            r2, _ = _run_child(
                {}, FULL, min(TPU_TIMEOUT_CAP_S, remaining() - 30)
            )
            if r2:
                if result:
                    r2["cpu_fallback_pairs_per_sec"] = result.get("value")
                result = r2
        elif pr2.reason != "ok":
            print(f"late probe {pr2.reason}: {pr2.detail}", file=sys.stderr)
    # 4) Cross-impl CPU data (VERDICT r3 weak #5): when the round ends on
    #    the CPU fallback, spend leftover budget on one 'onthefly' row at
    #    the same reduced shape so impl-comparison data exists chip-less.
    if (
        result
        and str(result.get("baseline_key", "")).startswith("cpu")
        and remaining() > 150
    ):
        r2, _ = _run_child(
            {
                "JAX_PLATFORMS": "cpu",
                "_BENCH_FORCE_PLATFORM": "cpu",
                "BENCH_CORR_IMPL": "onthefly",
            },
            SMALL,
            max(60.0, remaining() - 20),
        )
        if r2:
            _maybe_record_baseline(r2)
            result["pairs_per_sec_onthefly"] = r2["value"]
    if not result:
        result = {
            "metric": "raft_nc_dbl frame-pairs/sec/chip (no backend available)",
            "value": 0.0,
            "unit": "pairs/s",
            "vs_baseline": 0.0,
        }
    _maybe_record_baseline(result)
    print(json.dumps(result))


def _maybe_record_baseline(result: dict) -> None:
    """First successful recording for a (platform, impl, shape) key becomes
    the fixed baseline later rounds are measured against. The driver
    commits repo changes at round end, so the file persists."""
    key = result.get("baseline_key")
    if not key or not result.get("value"):
        return
    if result.get("fused_ok") is False:
        # A 'nconv=pallas' row whose fused kernel fell back to XLA must
        # not pin the '+nconv_pallas' baseline (ADVICE r3).
        print(
            f"not recording baseline {key}: fused kernel did not run",
            file=sys.stderr,
        )
        return
    baselines = _load_baselines()
    if key in baselines:
        return
    baselines[key] = result["value"]
    try:
        os.makedirs(os.path.dirname(_BASELINE_FILE), exist_ok=True)
        with open(_BASELINE_FILE, "w") as f:
            json.dump(baselines, f, indent=2, sort_keys=True)
            f.write("\n")
    except OSError as e:
        print(f"could not record baseline: {e}", file=sys.stderr)


if __name__ == "__main__":
    main()
