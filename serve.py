#!/usr/bin/env python
"""Flow-serving driver: run the online serving tier against a
deterministic synthetic open-loop request stream.

The serving analogue of train.py/evaluate.py (no reference counterpart —
the reference has no serving story). Builds one model + variables set,
stands up a :class:`raft_ncup_tpu.serving.FlowServer` (bounded admission
queue, anytime iteration budget, poison quarantine), warms the full
executable set, replays ``--num_requests`` synthetic requests at
``--interval_ms``, then drains and prints ONE JSON report line
(stats + latency percentiles + budget trajectory).

Graceful drain: SIGTERM/SIGINT (via ``resilience/preemption.py``) stops
submissions immediately, every request already admitted is flushed
through compute, and the process exits ``EXIT_PREEMPTED`` (75) — the
clean re-runnable shutdown, distinct from success and crash. Chaos
events (``--chaos "burst@8,poison@20,sigterm@40"``) drive the same
machinery deterministically (docs/SERVING.md has the full matrix).

Examples:
    python serve.py --platform cpu --num_requests 32 --size 96 128 \
        --iter_levels 12,6 --serve_batch_sizes 1,2
    python serve.py --restore_ckpt checkpoints/raft_nc_sintel \
        --chaos "burst@16" --queue_capacity 32
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def build_parser() -> argparse.ArgumentParser:
    from raft_ncup_tpu.cli import (
        add_model_args,
        add_platform_arg,
        add_serve_args,
    )

    parser = argparse.ArgumentParser(
        description="Serve RAFT / RAFT-NCUP flow over a synthetic "
        "open-loop request stream"
    )
    parser.add_argument("--restore_ckpt", default=None,
                        help="orbax run dir or torch .pth (default: "
                        "randomly initialized weights — the serving "
                        "machinery is shape-, not weight-, dependent)")
    parser.add_argument("--num_requests", type=int, default=32)
    parser.add_argument("--interval_ms", type=float, default=0.0,
                        help="steady inter-arrival gap (0 = as fast as "
                        "the submitting thread can go)")
    parser.add_argument("--size", type=int, nargs=2, default=[96, 128],
                        metavar=("H", "W"), help="request frame size")
    parser.add_argument("--burst_size", type=int, default=8,
                        help="requests per burst@N chaos event")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--style", default="smooth",
                        choices=["smooth", "rigid"],
                        help="synthetic traffic content generator")
    parser.add_argument("--chaos", default=None,
                        help="deterministic serving faults: comma-joined "
                        "burst@N / poison@N / sigterm@N "
                        "(resilience/chaos.py)")
    add_serve_args(parser)
    add_model_args(parser)
    add_platform_arg(parser)
    return parser


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from raft_ncup_tpu.cli import apply_platform

    apply_platform(args)

    from evaluate import load_variables
    from raft_ncup_tpu.cli import model_config_from_args, serve_config_from_args
    from raft_ncup_tpu.models.raft import RAFT
    from raft_ncup_tpu.resilience import EXIT_PREEMPTED, PreemptionHandler
    from raft_ncup_tpu.resilience.chaos import ChaosSpec
    from raft_ncup_tpu.serving import (
        FlowServer,
        SyntheticTraffic,
        nearest_rank_ms,
        replay,
    )

    model_cfg = model_config_from_args(args)
    serve_cfg = serve_config_from_args(args)
    chaos = ChaosSpec.parse(args.chaos)
    if chaos.active:
        print(f"chaos: {chaos.render()}", file=sys.stderr)

    model = RAFT(model_cfg)
    variables = load_variables(model, model_cfg, args.restore_ckpt)
    size_hw = (args.size[0], args.size[1])

    server = FlowServer(model, variables, serve_cfg)
    t0 = time.monotonic()
    compiled = server.warmup(size_hw)
    print(
        f"warmup: {compiled} executables compiled in "
        f"{time.monotonic() - t0:.1f}s "
        f"(batch_sizes={serve_cfg.batch_sizes} "
        f"iter_levels={serve_cfg.iter_levels})",
        file=sys.stderr,
    )

    traffic = SyntheticTraffic(
        size_hw,
        args.num_requests,
        seed=args.seed,
        interval_s=args.interval_ms / 1000.0,
        burst_size=args.burst_size,
        chaos=chaos,
        style=args.style,
    )
    t0 = time.monotonic()
    with PreemptionHandler() as preempt:
        handles, interrupted = replay(
            server, traffic, preempt=preempt,
            sigterm_after=chaos.sigterm_after,
        )
        stats = server.drain()
    wall = time.monotonic() - t0

    responses = [h.result(timeout=30.0) for h in handles]
    lat = [
        r.latency_s for r in responses if r.ok and r.latency_s is not None
    ]

    report = {
        "serve_requests": len(handles),
        "serve_ok": len(lat),
        "serve_wall_s": round(wall, 3),
        "serve_pairs_per_sec": (
            round(stats.completed / wall, 3) if wall > 0 else None
        ),
        "serve_p50_ms": nearest_rank_ms(lat, 0.50),
        "serve_p99_ms": nearest_rank_ms(lat, 0.99),
        "interrupted": interrupted,
        "completed": stats.completed,
        "shed": stats.shed,
        "timeouts": stats.timeouts,
        "rejected": stats.rejected,
        "errors": stats.errors,
        **server.report(),
    }
    print(json.dumps(report), flush=True)
    if interrupted:
        print(
            "serve: drained after signal — everything admitted was "
            "flushed; exiting EXIT_PREEMPTED",
            file=sys.stderr,
        )
        return EXIT_PREEMPTED
    return 0


if __name__ == "__main__":
    sys.exit(main())
