#!/usr/bin/env python
"""Flow-serving driver: run the online serving tier — or, with
``--stream``, the streaming video engine — against a deterministic
synthetic open-loop schedule.

The serving analogue of train.py/evaluate.py (no reference counterpart —
the reference has no serving story). Default mode builds one model +
variables set, stands up a :class:`raft_ncup_tpu.serving.FlowServer`
(bounded admission queue, anytime iteration budget, poison quarantine),
warms the full executable set, replays ``--num_requests`` synthetic
requests at ``--interval_ms``, then drains and prints ONE JSON report
line (stats + latency percentiles + budget trajectory).

``--stream`` mode stands up a
:class:`raft_ncup_tpu.streaming.StreamEngine` instead (fixed-capacity
slot table, device-resident warm start, per-stream fault isolation;
docs/STREAMING.md) and replays ``--n_streams`` concurrent streams of
``--frames_per_stream`` frames each.

Graceful drain (both modes): SIGTERM/SIGINT (via
``resilience/preemption.py``) stops submissions immediately, everything
already admitted is flushed through compute, and the process exits
``EXIT_PREEMPTED`` (75) — the clean re-runnable shutdown, distinct from
success and crash. Chaos events drive the same machinery
deterministically: ``--chaos "burst@8,poison@20,sigterm@40"`` for
serving, ``--chaos "corruptframe@5,abandon@9,sigterm@20"`` for
streaming (docs/SERVING.md and docs/STREAMING.md have the matrices).

Examples:
    python serve.py --platform cpu --num_requests 32 --size 96 128 \
        --iter_levels 12,6 --serve_batch_sizes 1,2
    python serve.py --restore_ckpt checkpoints/raft_nc_sintel \
        --chaos "burst@16" --queue_capacity 32
    python serve.py --platform cpu --stream --n_streams 3 \
        --frames_per_stream 6 --size 96 128 --stream_iters 8 \
        --chaos "corruptframe@7"
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import sys
import time


@contextlib.contextmanager
def _telemetry_export(args):
    """The periodic telemetry cadence for the run's duration: SLO
    burn-rate evaluation (ALWAYS — the budget controller's second
    degrade input and the report's verdict block are only truthful if
    the attached engine actually evaluates during the run, flags or
    not), plus bounded-JSONL snapshots (--telemetry_jsonl) and the
    atomically-rewritten healthz file (--healthz_file) when asked.

    Teardown order is the satellite contract: the PeriodicSnapshot's
    final tick (inner context) runs BEFORE the sink closes (outer), so
    the last report — the one describing the drained end state — can
    never hit a closed sink.
    """
    from raft_ncup_tpu.observability import (
        JsonlSink,
        PeriodicSnapshot,
        get_telemetry,
    )

    with contextlib.ExitStack() as stack:
        sink = None
        if args.telemetry_jsonl:
            sink = stack.enter_context(JsonlSink(args.telemetry_jsonl))
        stack.enter_context(PeriodicSnapshot(
            get_telemetry(), sink, args.telemetry_interval_s,
            healthz_path=args.healthz_file,
        ))
        yield


def _attach_observability(args, *, stream: bool):
    """Arm the consumer half on the process hub (docs/OBSERVABILITY.md):
    the declared SLO set (serve or stream — evaluated on the snapshot
    cadence, read by the budget controller and the healthz file) and
    the fault flight recorder. Returns the hub."""
    from raft_ncup_tpu.observability import (
        FlightRecorder,
        SloEngine,
        get_telemetry,
        serve_slos,
        stream_slos,
    )

    tel = get_telemetry()
    if args.flight_dir:
        tel.flight = FlightRecorder(args.flight_dir)
    specs = (
        stream_slos(args.stream_capacity,
                    window_scale=args.slo_window_scale)
        if stream
        else serve_slos(window_scale=args.slo_window_scale)
    )
    tel.slo = SloEngine(specs, tel)
    return tel


def build_parser() -> argparse.ArgumentParser:
    from raft_ncup_tpu.cli import (
        add_mesh_arg,
        add_model_args,
        add_platform_arg,
        add_serve_args,
        add_stream_args,
    )

    parser = argparse.ArgumentParser(
        description="Serve RAFT / RAFT-NCUP flow over a synthetic "
        "open-loop request stream"
    )
    parser.add_argument("--restore_ckpt", default=None,
                        help="orbax run dir or torch .pth (default: "
                        "randomly initialized weights — the serving "
                        "machinery is shape-, not weight-, dependent)")
    parser.add_argument("--num_requests", type=int, default=32)
    parser.add_argument("--interval_ms", type=float, default=0.0,
                        help="steady inter-arrival gap (0 = as fast as "
                        "the submitting thread can go)")
    parser.add_argument("--size", type=int, nargs=2, default=[96, 128],
                        metavar=("H", "W"), help="request frame size")
    parser.add_argument("--burst_size", type=int, default=8,
                        help="requests per burst@N chaos event")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--style", default="smooth",
                        choices=["smooth", "rigid"],
                        help="synthetic traffic content generator")
    parser.add_argument("--chaos", default=None,
                        help="deterministic faults: comma-joined "
                        "burst@N / poison@N / sigterm@N (serving) or "
                        "corruptframe@N / abandon@N / burst@N / "
                        "sigterm@N (--stream) — resilience/chaos.py")
    parser.add_argument("--stream", action="store_true",
                        help="drive the streaming video engine "
                        "(raft_ncup_tpu/streaming/) instead of the "
                        "request server")
    parser.add_argument("--report", action="store_true",
                        help="embed the full telemetry report "
                        "(observability.telemetry_report(): registry "
                        "snapshot, per-stage p50/p99, event accounting) "
                        "in the printed JSON — the same dict bench.py "
                        "reads")
    parser.add_argument("--telemetry_jsonl", default=None, metavar="PATH",
                        help="write periodic telemetry snapshots to this "
                        "bounded JSONL sink while serving "
                        "(observability/export.py)")
    parser.add_argument("--telemetry_interval_s", type=float, default=5.0,
                        help="snapshot cadence for --telemetry_jsonl / "
                        "--healthz_file (also the SLO burn-rate "
                        "evaluation cadence)")
    parser.add_argument("--healthz_file", default=None, metavar="PATH",
                        help="atomically rewrite this JSON file on the "
                        "telemetry cadence with per-subsystem health "
                        "states + SLO verdicts — the scrape surface a "
                        "fleet router polls (DRAINING rides the "
                        "SIGTERM/exit-75 contract; "
                        "docs/OBSERVABILITY.md)")
    parser.add_argument("--flight_dir",
                        default=os.environ.get(
                            "RAFT_NCUP_FLIGHT_DIR", "flight_recorder"
                        ),
                        help="fault flight-recorder directory: every "
                        "fault trigger (poison quarantine, anomaly "
                        "reset, SIGTERM drain, SLO page...) banks one "
                        "bounded atomic flight_<trigger>_<ts>.json "
                        "here ('' disables; scripts/postmortem.py "
                        "reads them)")
    parser.add_argument("--slo_window_scale", type=float, default=1.0,
                        help="scale the declared SLOs' 5m/1h burn-rate "
                        "windows (observability/slo.py) — e.g. 0.01 "
                        "for a seconds-scale demo/bench window")
    parser.add_argument("--n_streams", type=int, default=4,
                        help="[--stream] concurrent synthetic streams")
    parser.add_argument("--frames_per_stream", type=int, default=8,
                        help="[--stream] frames submitted per stream")
    add_serve_args(parser)
    add_stream_args(parser)
    add_mesh_arg(parser)
    add_model_args(parser)
    add_platform_arg(parser)
    return parser


def run_stream(args, model, variables) -> int:
    """--stream mode: replay a deterministic multi-stream schedule
    through the StreamEngine, drain, print one JSON report line."""
    from raft_ncup_tpu.cli import stream_config_from_args
    from raft_ncup_tpu.resilience import EXIT_PREEMPTED, PreemptionHandler
    from raft_ncup_tpu.resilience.chaos import ChaosSpec
    from raft_ncup_tpu.serving import nearest_rank_ms
    from raft_ncup_tpu.streaming import (
        StreamEngine,
        StreamTraffic,
        replay_streams,
    )

    chaos = ChaosSpec.parse(args.chaos)
    if chaos.active:
        print(f"chaos: {chaos.render()}", file=sys.stderr)
    size_hw = (args.size[0], args.size[1])
    stream_cfg = stream_config_from_args(args, size_hw)

    tel = _attach_observability(args, stream=True)
    engine = StreamEngine(model, variables, stream_cfg)
    t0 = time.monotonic()
    compiled = engine.warmup()
    print(
        f"warmup: {compiled} stream-step executables compiled in "
        f"{time.monotonic() - t0:.1f}s "
        f"(batch_sizes={stream_cfg.batch_sizes} "
        f"iters={stream_cfg.iters})",
        file=sys.stderr,
    )
    traffic = StreamTraffic(
        size_hw,
        args.n_streams,
        args.frames_per_stream,
        seed=args.seed,
        interval_s=args.interval_ms / 1000.0,
        burst_size=args.burst_size,
        chaos=chaos,
        style=args.style,
    )
    t0 = time.monotonic()
    with _telemetry_export(args), PreemptionHandler() as preempt:
        handles, interrupted = replay_streams(
            engine, traffic, preempt=preempt,
            sigterm_after=chaos.sigterm_after,
        )
        stats = engine.drain()
        if interrupted:
            # Fault trigger: the SIGTERM drain (exit 75), banked after
            # the flush so the dump describes the drained end state.
            tel.flight_dump(
                "preemption_drain",
                completed=stats.completed,
                shed_frames=stats.shed_frames,
            )
    wall = time.monotonic() - t0

    responses = [h.result(timeout=30.0) for h in handles]
    lat = [
        r.latency_s for r in responses if r.ok and r.latency_s is not None
    ]
    report = {
        "stream_frames": len(handles),
        "stream_ok": len(lat),
        "stream_wall_s": round(wall, 3),
        "stream_frames_per_sec": (
            round(stats.completed / wall, 3) if wall > 0 else None
        ),
        "stream_p50_ms": nearest_rank_ms(lat, 0.50),
        "stream_p99_ms": nearest_rank_ms(lat, 0.99),
        "interrupted": interrupted,
        "completed": stats.completed,
        "resets": stats.resets,
        "shed_streams": stats.shed_streams,
        "shed_frames": stats.shed_frames,
        "errors": stats.errors,
        **engine.report(),
        "slo": tel.slo.snapshot() if tel.slo is not None else None,
    }
    if args.report:
        from raft_ncup_tpu.observability import telemetry_report

        report["telemetry"] = telemetry_report()
    print(json.dumps(report), flush=True)
    if interrupted:
        print(
            "stream: drained after signal — every admitted frame was "
            "flushed; exiting EXIT_PREEMPTED",
            file=sys.stderr,
        )
        return EXIT_PREEMPTED
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from raft_ncup_tpu.cli import apply_platform

    apply_platform(args)

    from evaluate import load_variables
    from raft_ncup_tpu.cli import model_config_from_args, serve_config_from_args
    from raft_ncup_tpu.models.raft import RAFT
    from raft_ncup_tpu.resilience import EXIT_PREEMPTED, PreemptionHandler
    from raft_ncup_tpu.resilience.chaos import ChaosSpec
    from raft_ncup_tpu.serving import (
        FlowServer,
        SyntheticTraffic,
        nearest_rank_ms,
        replay,
    )

    model_cfg = model_config_from_args(args)
    model = RAFT(model_cfg)
    variables = load_variables(model, model_cfg, args.restore_ckpt)
    if args.stream:
        return run_stream(args, model, variables)

    serve_cfg = serve_config_from_args(args)
    chaos = ChaosSpec.parse(args.chaos)
    if chaos.active:
        print(f"chaos: {chaos.render()}", file=sys.stderr)

    size_hw = (args.size[0], args.size[1])

    tel = _attach_observability(args, stream=False)
    server = FlowServer(model, variables, serve_cfg)
    t0 = time.monotonic()
    compiled = server.warmup(size_hw)
    print(
        f"warmup: {compiled} executables compiled in "
        f"{time.monotonic() - t0:.1f}s "
        f"(batch_sizes={serve_cfg.batch_sizes} "
        f"iter_levels={serve_cfg.iter_levels})",
        file=sys.stderr,
    )

    traffic = SyntheticTraffic(
        size_hw,
        args.num_requests,
        seed=args.seed,
        interval_s=args.interval_ms / 1000.0,
        burst_size=args.burst_size,
        chaos=chaos,
        style=args.style,
    )
    t0 = time.monotonic()
    with _telemetry_export(args), PreemptionHandler() as preempt:
        handles, interrupted = replay(
            server, traffic, preempt=preempt,
            sigterm_after=chaos.sigterm_after,
        )
        stats = server.drain()
        if interrupted:
            # Fault trigger: the SIGTERM drain (exit 75), banked after
            # the flush so the dump describes the drained end state.
            tel.flight_dump(
                "preemption_drain",
                completed=stats.completed, shed=stats.shed,
            )
    wall = time.monotonic() - t0

    responses = [h.result(timeout=30.0) for h in handles]
    lat = [
        r.latency_s for r in responses if r.ok and r.latency_s is not None
    ]

    report = {
        "serve_requests": len(handles),
        "serve_ok": len(lat),
        "serve_wall_s": round(wall, 3),
        "serve_pairs_per_sec": (
            round(stats.completed / wall, 3) if wall > 0 else None
        ),
        "serve_p50_ms": nearest_rank_ms(lat, 0.50),
        "serve_p99_ms": nearest_rank_ms(lat, 0.99),
        "interrupted": interrupted,
        "completed": stats.completed,
        "shed": stats.shed,
        "timeouts": stats.timeouts,
        "rejected": stats.rejected,
        "errors": stats.errors,
        **server.report(),
        "slo": tel.slo.snapshot() if tel.slo is not None else None,
    }
    if args.report:
        from raft_ncup_tpu.observability import telemetry_report

        report["telemetry"] = telemetry_report()
    print(json.dumps(report), flush=True)
    if interrupted:
        print(
            "serve: drained after signal — everything admitted was "
            "flushed; exiting EXIT_PREEMPTED",
            file=sys.stderr,
        )
        return EXIT_PREEMPTED
    return 0


if __name__ == "__main__":
    sys.exit(main())
