#!/usr/bin/env python
"""Flow-serving driver: run the online serving tier — or, with
``--stream``, the streaming video engine — against a deterministic
synthetic open-loop schedule.

The serving analogue of train.py/evaluate.py (no reference counterpart —
the reference has no serving story). Default mode builds one model +
variables set, stands up a :class:`raft_ncup_tpu.serving.FlowServer`
(bounded admission queue, anytime iteration budget, poison quarantine),
warms the full executable set, replays ``--num_requests`` synthetic
requests at ``--interval_ms``, then drains and prints ONE JSON report
line (stats + latency percentiles + budget trajectory).

``--stream`` mode stands up a
:class:`raft_ncup_tpu.streaming.StreamEngine` instead (fixed-capacity
slot table, device-resident warm start, per-stream fault isolation;
docs/STREAMING.md) and replays ``--n_streams`` concurrent streams of
``--frames_per_stream`` frames each.

Graceful drain (both modes): SIGTERM/SIGINT (via
``resilience/preemption.py``) stops submissions immediately, everything
already admitted is flushed through compute, and the process exits
``EXIT_PREEMPTED`` (75) — the clean re-runnable shutdown, distinct from
success and crash. Chaos events drive the same machinery
deterministically: ``--chaos "burst@8,poison@20,sigterm@40"`` for
serving, ``--chaos "corruptframe@5,abandon@9,sigterm@20"`` for
streaming (docs/SERVING.md and docs/STREAMING.md have the matrices).

Examples:
    python serve.py --platform cpu --num_requests 32 --size 96 128 \
        --iter_levels 12,6 --serve_batch_sizes 1,2
    python serve.py --restore_ckpt checkpoints/raft_nc_sintel \
        --chaos "burst@16" --queue_capacity 32
    python serve.py --platform cpu --stream --n_streams 3 \
        --frames_per_stream 6 --size 96 128 --stream_iters 8 \
        --chaos "corruptframe@7"
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import sys
import threading
import time

from raft_ncup_tpu.utils.knobs import knob_str


@contextlib.contextmanager
def _telemetry_export(args):
    """The periodic telemetry cadence for the run's duration: SLO
    burn-rate evaluation (ALWAYS — the budget controller's second
    degrade input and the report's verdict block are only truthful if
    the attached engine actually evaluates during the run, flags or
    not), plus bounded-JSONL snapshots (--telemetry_jsonl) and the
    atomically-rewritten healthz file (--healthz_file) when asked.

    Teardown order is the satellite contract: the PeriodicSnapshot's
    final tick (inner context) runs BEFORE the sink closes (outer), so
    the last report — the one describing the drained end state — can
    never hit a closed sink.
    """
    from raft_ncup_tpu.observability import (
        JsonlSink,
        PeriodicSnapshot,
        get_telemetry,
    )

    with contextlib.ExitStack() as stack:
        sink = None
        if args.telemetry_jsonl:
            sink = stack.enter_context(JsonlSink(args.telemetry_jsonl))
        stack.enter_context(PeriodicSnapshot(
            get_telemetry(), sink, args.telemetry_interval_s,
            healthz_path=args.healthz_file,
        ))
        yield


def _attach_observability(args, *, stream: bool):
    """Arm the consumer half on the process hub (docs/OBSERVABILITY.md):
    the declared SLO set (serve or stream — evaluated on the snapshot
    cadence, read by the budget controller and the healthz file) and
    the fault flight recorder. Returns the hub."""
    from raft_ncup_tpu.observability import (
        FlightRecorder,
        SloEngine,
        get_telemetry,
        serve_slos,
        stream_slos,
    )

    tel = get_telemetry()
    if args.flight_dir:
        tel.flight = FlightRecorder(args.flight_dir)
    specs = (
        stream_slos(args.stream_capacity,
                    window_scale=args.slo_window_scale)
        if stream
        else serve_slos(window_scale=args.slo_window_scale)
    )
    tel.slo = SloEngine(specs, tel)
    return tel


def build_parser() -> argparse.ArgumentParser:
    from raft_ncup_tpu.cli import (
        add_mesh_arg,
        add_model_args,
        add_platform_arg,
        add_serve_args,
        add_stream_args,
        str2bool as _str2bool,
    )

    parser = argparse.ArgumentParser(
        description="Serve RAFT / RAFT-NCUP flow over a synthetic "
        "open-loop request stream"
    )
    parser.add_argument("--restore_ckpt", default=None,
                        help="orbax run dir or torch .pth (default: "
                        "randomly initialized weights — the serving "
                        "machinery is shape-, not weight-, dependent)")
    parser.add_argument("--num_requests", type=int, default=32)
    parser.add_argument("--interval_ms", type=float, default=0.0,
                        help="steady inter-arrival gap (0 = as fast as "
                        "the submitting thread can go)")
    parser.add_argument("--size", type=int, nargs=2, default=[96, 128],
                        metavar=("H", "W"), help="request frame size")
    parser.add_argument("--burst_size", type=int, default=8,
                        help="requests per burst@N chaos event")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--style", default="smooth",
                        choices=["smooth", "rigid"],
                        help="synthetic traffic content generator")
    parser.add_argument("--chaos", default=None,
                        help="deterministic faults: comma-joined "
                        "burst@N / poison@N / sigterm@N (serving) or "
                        "corruptframe@N / abandon@N / burst@N / "
                        "sigterm@N (--stream) — resilience/chaos.py")
    parser.add_argument("--stream", action="store_true",
                        help="drive the streaming video engine "
                        "(raft_ncup_tpu/streaming/) instead of the "
                        "request server")
    parser.add_argument("--replica_socket", default=None, metavar="ADDR",
                        help="replica-server mode (raft_ncup_tpu/fleet/; "
                        "docs/FLEET.md): serve request/frame messages "
                        "over this wire address — a Unix-domain-socket "
                        "path or host:port for TCP "
                        "(length-prefixed "
                        "JSON header + raw ndarray frames) through the "
                        "FlowServer (+ StreamEngine) instead of "
                        "replaying synthetic traffic — the child "
                        "process a fleet ReplicaSupervisor spawns; "
                        "SIGTERM drains (healthz shows DRAINING before "
                        "the flush) and exits 75")
    parser.add_argument("--replica_index", type=int, default=0,
                        help="[--replica_socket] this replica's index "
                        "in the fleet topology (report + telemetry "
                        "correlation)")
    parser.add_argument("--replica_streams", type=_str2bool,
                        nargs="?", const=True, default=True,
                        help="[--replica_socket] also run a "
                        "StreamEngine so the replica serves stream "
                        "frames alongside one-shot requests "
                        "(false = request-only replica)")
    parser.add_argument("--report", action="store_true",
                        help="embed the full telemetry report "
                        "(observability.telemetry_report(): registry "
                        "snapshot, per-stage p50/p99, event accounting) "
                        "in the printed JSON — the same dict bench.py "
                        "reads")
    parser.add_argument("--telemetry_jsonl", default=None, metavar="PATH",
                        help="write periodic telemetry snapshots to this "
                        "bounded JSONL sink while serving "
                        "(observability/export.py)")
    parser.add_argument("--telemetry_interval_s", type=float, default=5.0,
                        help="snapshot cadence for --telemetry_jsonl / "
                        "--healthz_file (also the SLO burn-rate "
                        "evaluation cadence)")
    parser.add_argument("--healthz_file", default=None, metavar="PATH",
                        help="atomically rewrite this JSON file on the "
                        "telemetry cadence with per-subsystem health "
                        "states + SLO verdicts — the scrape surface a "
                        "fleet router polls (DRAINING rides the "
                        "SIGTERM/exit-75 contract; "
                        "docs/OBSERVABILITY.md)")
    parser.add_argument("--flight_dir",
                        default=knob_str(
                            "RAFT_NCUP_FLIGHT_DIR",
                            default="flight_recorder",
                        ),
                        help="fault flight-recorder directory: every "
                        "fault trigger (poison quarantine, anomaly "
                        "reset, SIGTERM drain, SLO page...) banks one "
                        "bounded atomic flight_<trigger>_<ts>.json "
                        "here ('' disables; scripts/postmortem.py "
                        "reads them)")
    parser.add_argument("--slo_window_scale", type=float, default=1.0,
                        help="scale the declared SLOs' 5m/1h burn-rate "
                        "windows (observability/slo.py) — e.g. 0.01 "
                        "for a seconds-scale demo/bench window")
    parser.add_argument("--n_streams", type=int, default=4,
                        help="[--stream] concurrent synthetic streams")
    parser.add_argument("--frames_per_stream", type=int, default=8,
                        help="[--stream] frames submitted per stream")
    add_serve_args(parser)
    add_stream_args(parser)
    add_mesh_arg(parser)
    add_model_args(parser)
    add_platform_arg(parser)
    return parser


def run_stream(args, model, variables) -> int:
    """--stream mode: replay a deterministic multi-stream schedule
    through the StreamEngine, drain, print one JSON report line."""
    from raft_ncup_tpu.cli import stream_config_from_args
    from raft_ncup_tpu.resilience import EXIT_PREEMPTED, PreemptionHandler
    from raft_ncup_tpu.resilience.chaos import ChaosSpec
    from raft_ncup_tpu.serving import nearest_rank_ms
    from raft_ncup_tpu.streaming import (
        StreamEngine,
        StreamTraffic,
        replay_streams,
    )

    chaos = ChaosSpec.parse(args.chaos)
    if chaos.active:
        print(f"chaos: {chaos.render()}", file=sys.stderr)
    size_hw = (args.size[0], args.size[1])
    stream_cfg = stream_config_from_args(args, size_hw)

    tel = _attach_observability(args, stream=True)
    engine = StreamEngine(model, variables, stream_cfg)
    t0 = time.monotonic()
    compiled = engine.warmup()
    # Replica identity for the healthz file (docs/FLEET.md): the warmed
    # step set + mesh fingerprint a fleet router routes on.
    tel.identity.update({
        "mesh": engine.report()["mesh"],
        "warmed": [list(x) for x in engine.warmed],
    })
    print(
        f"warmup: {compiled} stream-step executables compiled in "
        f"{time.monotonic() - t0:.1f}s "
        f"(batch_sizes={stream_cfg.batch_sizes} "
        f"iters={stream_cfg.iters})",
        file=sys.stderr,
    )
    traffic = StreamTraffic(
        size_hw,
        args.n_streams,
        args.frames_per_stream,
        seed=args.seed,
        interval_s=args.interval_ms / 1000.0,
        burst_size=args.burst_size,
        chaos=chaos,
        style=args.style,
    )
    t0 = time.monotonic()
    with _telemetry_export(args), PreemptionHandler() as preempt:
        handles, interrupted = replay_streams(
            engine, traffic, preempt=preempt,
            sigterm_after=chaos.sigterm_after,
        )
        stats = engine.drain()
        if interrupted:
            # Fault trigger: the SIGTERM drain (exit 75), banked after
            # the flush so the dump describes the drained end state.
            tel.flight_dump(
                "preemption_drain",
                completed=stats.completed,
                shed_frames=stats.shed_frames,
            )
    wall = time.monotonic() - t0

    responses = [h.result(timeout=30.0) for h in handles]
    lat = [
        r.latency_s for r in responses if r.ok and r.latency_s is not None
    ]
    report = {
        "stream_frames": len(handles),
        "stream_ok": len(lat),
        "stream_wall_s": round(wall, 3),
        "stream_frames_per_sec": (
            round(stats.completed / wall, 3) if wall > 0 else None
        ),
        "stream_p50_ms": nearest_rank_ms(lat, 0.50),
        "stream_p99_ms": nearest_rank_ms(lat, 0.99),
        "interrupted": interrupted,
        "completed": stats.completed,
        "resets": stats.resets,
        "shed_streams": stats.shed_streams,
        "shed_frames": stats.shed_frames,
        "errors": stats.errors,
        **engine.report(),
        "slo": tel.slo.snapshot() if tel.slo is not None else None,
    }
    if args.report:
        from raft_ncup_tpu.inference.costs import get_cost_ledger
        from raft_ncup_tpu.observability import telemetry_report

        report["telemetry"] = telemetry_report()
        # The executable cost ledger (inference/costs.py): per-warmed-
        # executable flops/bytes/compile-time/memory-stats — host dicts
        # recorded at compile time, no sync to read.
        report["cost_ledger"] = get_cost_ledger().snapshot()
    print(json.dumps(report), flush=True)
    if interrupted:
        print(
            "stream: drained after signal — every admitted frame was "
            "flushed; exiting EXIT_PREEMPTED",
            file=sys.stderr,
        )
        return EXIT_PREEMPTED
    return 0


def run_replica(args, model, variables) -> int:
    """--replica_socket mode: one fleet replica (docs/FLEET.md).

    Serves ``request``/``frame`` messages from the router over a Unix
    domain socket through the existing FlowServer/StreamEngine — the
    replica IS the single-process serving tier, plus a wire. The
    service window runs under the runtime guards (0 recompiles after
    warmup, 0 implicit host transfers — the per-replica counters the
    fleet bench row asserts), the healthz file advertises the replica
    identity a router routes on (pid, mesh, warmed executable set), and
    SIGTERM runs the drain contract: healthz shows DRAINING *before*
    the flush, everything admitted is flushed, exit 75.
    """
    import socket as socket_mod
    from concurrent.futures import ThreadPoolExecutor

    from raft_ncup_tpu.analysis.guards import (
        GuardStats,
        RecompileWatchdog,
        forbid_host_transfers,
    )
    from raft_ncup_tpu.cli import (
        serve_config_from_args,
        stream_config_from_args,
    )
    from raft_ncup_tpu.fleet.wire import Transport, recv_msg, send_msg
    from raft_ncup_tpu.observability import write_healthz
    from raft_ncup_tpu.resilience import EXIT_PREEMPTED, PreemptionHandler
    from raft_ncup_tpu.serving import FlowServer

    size_hw = (args.size[0], args.size[1])
    serve_cfg = serve_config_from_args(args)
    tel = _attach_observability(args, stream=False)
    server = FlowServer(model, variables, serve_cfg)
    engine = None
    if args.replica_streams:
        from raft_ncup_tpu.observability import (
            SloEngine,
            serve_slos,
            stream_slos,
        )
        from raft_ncup_tpu.streaming import StreamEngine

        # A replica serving BOTH tiers declares BOTH SLO sets: a
        # replica that sheds every stream frame while its serve tier is
        # healthy must page (and read degraded in healthz), or the
        # router keeps homing streams on it.
        tel.slo = SloEngine(
            serve_slos(window_scale=args.slo_window_scale)
            + stream_slos(args.stream_capacity,
                          window_scale=args.slo_window_scale),
            tel,
        )
        stream_cfg = stream_config_from_args(args, size_hw)
        engine = StreamEngine(model, variables, stream_cfg)
    t0 = time.monotonic()
    compiled = server.warmup(size_hw)
    if engine is not None:
        compiled += engine.warmup()
    # The replica identity the healthz file advertises (write_healthz
    # merges Telemetry.identity): the warmed (shape, batch, iters)
    # executable set is what the router's shape-aware routing reads.
    tel.identity.update({
        "replica": args.replica_index,
        "mesh": server.report()["mesh"],
        "warmed": [list(x) for x in server.warmed],
    })
    if engine is not None:
        tel.identity["stream_warmed"] = [list(x) for x in engine.warmed]
    print(
        f"replica {args.replica_index}: {compiled} executables compiled "
        f"in {time.monotonic() - t0:.1f}s; serving on "
        f"{args.replica_socket}",
        file=sys.stderr,
    )

    # The address string decides the socket family (UDS path vs
    # host:port) — the same string the FleetConfig argv carried, so a
    # topology moves to TCP without touching the replica code path.
    transport = Transport.parse(args.replica_socket)
    lsock = transport.listen(16)
    lsock.settimeout(0.1)

    pool = ThreadPoolExecutor(
        max_workers=32, thread_name_prefix="replica-respond"
    )
    conns: list = []

    def respond(conn, send_lock, rid, handle, t_recv, trace_id) -> None:
        """Wait for one request's terminal response and wire it back
        (each handle completes exactly once; the drain flush completes
        every admitted handle, so the bounded wait only trips if the
        serving tier itself wedged)."""
        try:
            r = handle.result(timeout=600.0)
        except TimeoutError:
            r = None
        header = {
            "kind": "response",
            "id": rid,
            "status": "error" if r is None else r.status,
            "iters": None if r is None else r.iters,
            "latency_s": None if r is None else r.latency_s,
            "retry_after_s": None if r is None else r.retry_after_s,
            "detail": "replica response timeout" if r is None else r.detail,
            # Per-hop timing stamps on THIS replica's monotonic clock
            # (receive -> done); the router translates them through the
            # handshake offset into fleet_hop_wire/replica/return_ms.
            # Optional fields: an old router just ignores them.
            "t_recv_s": t_recv,
            "t_done_s": time.monotonic(),
        }
        if trace_id is not None:
            header["trace"] = {"trace_id": trace_id}
        arrays = (r.flow,) if (r is not None and r.flow is not None) else ()
        try:
            with send_lock:
                send_msg(conn, header, arrays)
        except OSError:
            # The router hung up (death detection already failed the
            # request over on its side); nothing to deliver to.
            tel.inc("replica_response_undeliverable_total")

    def serve_conn(conn) -> None:
        from raft_ncup_tpu.observability.spans import TraceContext

        send_lock = threading.Lock()
        try:
            while True:
                msg = recv_msg(conn)
                if msg is None:
                    break
                t_recv = time.monotonic()
                header, arrays = msg
                kind = header.get("kind")
                if kind == "ping":
                    # Clock handshake: echo the router's t0 and stamp
                    # our monotonic clock, so the router can estimate
                    # replica_mono - router_mono (rtt-halved).
                    with send_lock:
                        send_msg(conn, {
                            "kind": "pong", "pid": os.getpid(),
                            "t0": header.get("t0"),
                            "t_mono": time.monotonic(),
                        })
                    continue
                if kind == "set_telemetry":
                    # Bench's fleet telemetry-overhead window: flip the
                    # hub in place on the warm replica (the same
                    # Telemetry.enabled bool the serve row flips
                    # in-process). Guards and product stats keep
                    # counting either way.
                    tel.enabled = bool(header.get("enabled", True))
                    with send_lock:
                        send_msg(conn, {
                            "kind": "telemetry_ack",
                            "enabled": tel.enabled,
                            "replica": args.replica_index,
                        })
                    continue
                rid = int(header.get("id", -1))
                # Adopt the inbound trace context (an OPTIONAL header
                # field — frames without it parse identically): the
                # replica's admission/batch/device spans then carry the
                # router's trace_id, and the measured wire hop lands as
                # a replica-side span under the same trace.
                ctx = TraceContext.from_wire(header.get("trace"))
                tid = None
                if ctx is not None:
                    tid = ctx.trace_id
                    if ctx.sent_s is not None:
                        tel.observe_ms(
                            "fleet_wire_hop",
                            max(0.0, (t_recv - (ctx.sent_s
                                                + ctx.clock_offset_s))
                                * 1e3),
                            trace_id=tid, request_id=rid,
                            parent_span_id=ctx.span_id,
                            replica=args.replica_index,
                        )
                if kind == "request" and len(arrays) == 2:
                    handle = server.submit(
                        arrays[0], arrays[1],
                        deadline_s=header.get("deadline_s"),
                        request_id=rid,
                        trace_id=tid,
                    )
                elif kind == "frame" and len(arrays) == 2:
                    if engine is None:
                        with send_lock:
                            send_msg(conn, {
                                "kind": "response", "id": rid,
                                "status": "rejected",
                                "detail": "request-only replica "
                                "(replica_streams=false)",
                            })
                        continue
                    handle = engine.submit(
                        str(header.get("stream_id")),
                        arrays[0], arrays[1],
                        frame_index=header.get("frame_index"),
                        request_id=rid,
                        trace_id=tid,
                    )
                else:
                    with send_lock:
                        send_msg(conn, {
                            "kind": "response", "id": rid,
                            "status": "rejected",
                            "detail": f"bad message kind {kind!r}",
                        })
                    continue
                pool.submit(respond, conn, send_lock, rid, handle,
                            t_recv, tid)
        except (ConnectionError, OSError, ValueError) as e:
            print(f"replica connection dropped: {e!r}", file=sys.stderr)
        finally:
            try:
                conn.close()
            except OSError:
                pass

    stats = GuardStats()
    interrupted = False
    # Guards arm AFTER warmup: every compile from here on is a
    # steady-state recompile, every implicit pull a leak — the
    # per-replica counters the fleet bench row requires to be 0.
    with _telemetry_export(args), PreemptionHandler() as preempt, \
            RecompileWatchdog() as wd, \
            forbid_host_transfers(stats, raise_on_violation=False):
        while not preempt.requested:
            try:
                conn, _ = lsock.accept()
            except socket_mod.timeout:
                continue
            except OSError:
                break
            conns.append(conn)
            threading.Thread(
                target=serve_conn, args=(conn,),
                name="replica-conn", daemon=True,
            ).start()
        interrupted = preempt.requested
        # Drain contract: DRAINING must be visible to a healthz poller
        # BEFORE the flush — the router stops routing here while the
        # in-flight work completes. The explicit write makes the
        # ordering independent of the snapshot cadence.
        server.health.draining("sigterm")
        if engine is not None:
            engine.health.draining("sigterm")
        if args.healthz_file:
            write_healthz(args.healthz_file, tel,
                          interval_s=args.telemetry_interval_s)
        sstats = server.drain()
        estats = engine.drain() if engine is not None else None
        if interrupted:
            tel.flight_dump(
                "preemption_drain",
                replica=args.replica_index,
                completed=sstats.completed,
                shed=sstats.shed,
            )
        # Every handle is now terminal; let the responders flush.
        pool.shutdown(wait=True)
        # Orderly close of every connection still open: peers get EOF
        # from the drain, not from process exit.
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass
    lsock.close()
    transport.cleanup()

    report = {
        "replica": args.replica_index,
        "interrupted": interrupted,
        "recompiles": wd.count,
        "host_transfers": stats.host_transfers,
        "completed": sstats.completed,
        "shed": sstats.shed,
        "timeouts": sstats.timeouts,
        "rejected": sstats.rejected,
        "errors": sstats.errors,
        **server.report(),
        "slo": tel.slo.snapshot() if tel.slo is not None else None,
    }
    if estats is not None:
        report["stream_completed"] = estats.completed
        report["stream_resets"] = estats.resets
        report["stream_shed_frames"] = estats.shed_frames
        report["stream_errors"] = estats.errors
        report["stream_report"] = engine.report()
    if args.report:
        from raft_ncup_tpu.inference.costs import get_cost_ledger
        from raft_ncup_tpu.observability import telemetry_report

        report["telemetry"] = telemetry_report()
        # The executable cost ledger (inference/costs.py): per-warmed-
        # executable flops/bytes/compile-time/memory-stats — host dicts
        # recorded at compile time, no sync to read.
        report["cost_ledger"] = get_cost_ledger().snapshot()
    print(json.dumps(report), flush=True)
    if interrupted:
        print(
            f"replica {args.replica_index}: drained after signal — "
            "everything admitted was flushed; exiting EXIT_PREEMPTED",
            file=sys.stderr,
        )
        return EXIT_PREEMPTED
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from raft_ncup_tpu.cli import apply_platform

    apply_platform(args)

    from evaluate import load_variables
    from raft_ncup_tpu.cli import model_config_from_args, serve_config_from_args
    from raft_ncup_tpu.models.raft import RAFT
    from raft_ncup_tpu.resilience import EXIT_PREEMPTED, PreemptionHandler
    from raft_ncup_tpu.resilience.chaos import ChaosSpec
    from raft_ncup_tpu.serving import (
        FlowServer,
        SyntheticTraffic,
        nearest_rank_ms,
        replay,
    )

    model_cfg = model_config_from_args(args)
    model = RAFT(model_cfg)
    variables = load_variables(model, model_cfg, args.restore_ckpt)
    if args.replica_socket:
        return run_replica(args, model, variables)
    if args.stream:
        return run_stream(args, model, variables)

    serve_cfg = serve_config_from_args(args)
    chaos = ChaosSpec.parse(args.chaos)
    if chaos.active:
        print(f"chaos: {chaos.render()}", file=sys.stderr)

    size_hw = (args.size[0], args.size[1])

    tel = _attach_observability(args, stream=False)
    server = FlowServer(model, variables, serve_cfg)
    t0 = time.monotonic()
    compiled = server.warmup(size_hw)
    # Replica identity for the healthz file (docs/FLEET.md): the warmed
    # (shape, batch, iters) executable set + mesh fingerprint a fleet
    # router's shape-aware routing reads.
    tel.identity.update({
        "mesh": server.report()["mesh"],
        "warmed": [list(x) for x in server.warmed],
    })
    print(
        f"warmup: {compiled} executables compiled in "
        f"{time.monotonic() - t0:.1f}s "
        f"(batch_sizes={serve_cfg.batch_sizes} "
        f"iter_levels={serve_cfg.iter_levels})",
        file=sys.stderr,
    )

    traffic = SyntheticTraffic(
        size_hw,
        args.num_requests,
        seed=args.seed,
        interval_s=args.interval_ms / 1000.0,
        burst_size=args.burst_size,
        chaos=chaos,
        style=args.style,
    )
    t0 = time.monotonic()
    with _telemetry_export(args), PreemptionHandler() as preempt:
        handles, interrupted = replay(
            server, traffic, preempt=preempt,
            sigterm_after=chaos.sigterm_after,
        )
        stats = server.drain()
        if interrupted:
            # Fault trigger: the SIGTERM drain (exit 75), banked after
            # the flush so the dump describes the drained end state.
            tel.flight_dump(
                "preemption_drain",
                completed=stats.completed, shed=stats.shed,
            )
    wall = time.monotonic() - t0

    responses = [h.result(timeout=30.0) for h in handles]
    lat = [
        r.latency_s for r in responses if r.ok and r.latency_s is not None
    ]

    report = {
        "serve_requests": len(handles),
        "serve_ok": len(lat),
        "serve_wall_s": round(wall, 3),
        "serve_pairs_per_sec": (
            round(stats.completed / wall, 3) if wall > 0 else None
        ),
        "serve_p50_ms": nearest_rank_ms(lat, 0.50),
        "serve_p99_ms": nearest_rank_ms(lat, 0.99),
        "interrupted": interrupted,
        "completed": stats.completed,
        "shed": stats.shed,
        "timeouts": stats.timeouts,
        "rejected": stats.rejected,
        "errors": stats.errors,
        **server.report(),
        "slo": tel.slo.snapshot() if tel.slo is not None else None,
    }
    if args.report:
        from raft_ncup_tpu.inference.costs import get_cost_ledger
        from raft_ncup_tpu.observability import telemetry_report

        report["telemetry"] = telemetry_report()
        # The executable cost ledger (inference/costs.py): per-warmed-
        # executable flops/bytes/compile-time/memory-stats — host dicts
        # recorded at compile time, no sync to read.
        report["cost_ledger"] = get_cost_ledger().snapshot()
    print(json.dumps(report), flush=True)
    if interrupted:
        print(
            "serve: drained after signal — everything admitted was "
            "flushed; exiting EXIT_PREEMPTED",
            file=sys.stderr,
        )
        return EXIT_PREEMPTED
    return 0


if __name__ == "__main__":
    sys.exit(main())
