#!/bin/bash
# TPU re-make of the reference Sintel fine-tune (reference:
# train_raft_nc_sintel.sh:5-19): 50k steps, crop 368x768, gamma 0.85,
# warm-started from the things-stage RAFT-NCUP checkpoint.
set -e
EXP=raft_nc_sintel_ft

python -u train.py \
  --name "$EXP" \
  --model raft_nc_dbl \
  --load_pretrained models/raft-sintel.pth \
  --stage sintel \
  --validation sintel \
  --num_steps 50000 \
  --batch_size 6 \
  --lr 0.000125 \
  --image_size 368 768 \
  --gamma 0.85 \
  --optimizer adamw \
  --scheduler cyclic \
  --final_upsampling=NConvUpsampler \
  --final_upsampling_scale=4 \
  --final_upsampling_use_data_for_guidance=True \
  --final_upsampling_channels_to_batch=True \
  --interp_net=NConvUNet \
  --interp_net_channels_multiplier=2 \
  --interp_net_num_downsampling=1 \
  --interp_net_data_pooling="conf_based" \
  --interp_net_encoder_filter_sz=5 \
  --interp_net_decoder_filter_sz=3 \
  --interp_net_out_filter_sz=1 \
  --interp_net_shared_encoder=True \
  --interp_net_use_bias=False \
  --weights_est_net=Simple \
  --weights_est_net_num_ch="[64, 32]" \
  --weights_est_net_filter_sz="[3, 3, 1]" \
  --weights_est_net_dilation="[1, 1, 1]" \
  "$@"
