#!/bin/bash
# Synthetic convergence artifact (VERDICT r3 next-round #4): a data-free
# training run sized to a 1-core host (~100 min), logging held-out
# validate_synthetic EPE every 200 steps. Proves the training loop
# *learns* (EPE >=5x down from init: 7.21 untrained at these settings),
# not just that it runs — the reference's validation-as-testing cadence
# (reference: train.py:229-245) applied to the procedural dataset since
# no real dataset ships in this environment. Curve recorded in
# docs/PERF.md; full log in checkpoints/synth_r4/log.txt.
set -e
cd "$(dirname "$0")/.."
python train.py \
    --name synth_r4 \
    --stage chairs \
    --model raft --small \
    --synthetic_ok \
    --platform cpu \
    --num_steps 4000 \
    --image_size 64 96 \
    --batch_size 2 \
    --iters 4 \
    --lr 4e-4 \
    --wdecay 1e-5 \
    --val_freq 200 \
    --sum_freq 50 \
    --validation synthetic
