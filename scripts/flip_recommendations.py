#!/usr/bin/env python
"""Data-driven kernel-default recommendations from a bench record.

Reads one bench.py JSON record (file argument or stdin) and prints which
implementation defaults the measurements support flipping:

- ``ModelConfig.corr_impl`` (raft_ncup_tpu/config.py) — 'volume' vs
  'onthefly' vs 'pallas' (reference hot path: core/corr.py:13-44);
- ``RAFT_NCUP_NCONV_IMPL`` (raft_ncup_tpu/ops/nconv.py) — 'xla' vs the
  fused Pallas NConv kernel.

Defaults only flip on ACCELERATOR data: CPU rows order kernels by how
well they suit a host CPU, not the MXU/VMEM tradeoffs the kernels were
built around (docs/PERF.md: volume beats onthefly on CPU at the small
shape for exactly this reason).
"""

from __future__ import annotations

import json
import sys

MARGIN = 1.03  # >=3% win required to recommend changing a default


def recommend(record: dict) -> list[str]:
    lines = []
    key = str(record.get("baseline_key", ""))
    if key.startswith("cpu") or not key:
        # Kernel defaults never flip on CPU data, but the eval-pipeline
        # row's invariant verdict still matters (a leaking loop is a
        # leaking loop on any backend).
        return [
            "no accelerator measurement in this record "
            f"(baseline_key={key or 'absent'!r}); defaults stay "
            "corr_impl='volume', RAFT_NCUP_NCONV_IMPL='xla' pending TPU data"
        ] + _val_row_lines(record) + _serve_row_lines(record) + _bf16_row_lines(
            record
        ) + _highres_row_lines(record) + _uhd_row_lines(
            record
        ) + _pipeline_lines(record) + _earlyexit_lines(
            record
        ) + _fleet_lines(
            record
        ) + _elasticity_lines(record) + _telemetry_lines(record)

    corr = {"volume": record.get("value")}
    for tag in ("onthefly", "pallas"):
        v = record.get(f"pairs_per_sec_{tag}")
        if v:
            corr[tag] = v
    corr = {k: v for k, v in corr.items() if v}
    if not corr or "volume" not in corr:
        # Without the volume row there is no corr comparison: a
        # watchdog-killed primary attempt can leave only variant rows (or
        # none), and flipping on variant-vs-variant data would change the
        # default with no baseline evidence (ADVICE r5). The nconv section
        # below still runs — its fell-back diagnosis needs no baseline.
        lines.append(
            "corr_impl: no volume baseline in record "
            f"(measured: {sorted(corr) or 'none'}); defaults stay — "
            "rerun bench for the primary row"
        )
    else:
        best = max(corr, key=corr.get)
        if len(corr) < 2:
            lines.append(
                f"corr_impl: only {list(corr)} measured; no comparison possible"
            )
        elif best != "volume" and corr[best] >= MARGIN * corr.get("volume", 0):
            lines.append(
                f"corr_impl: FLIP default 'volume' -> '{best}' "
                f"({corr[best]:.2f} vs {corr['volume']:.2f} pairs/s; "
                "edit raft_ncup_tpu/config.py ModelConfig.corr_impl)"
            )
        else:
            lines.append(
                f"corr_impl: keep 'volume' ({ {k: round(v, 2) for k, v in corr.items()} })"
            )

        if "corr_pallas_levels" in record and "pallas" in corr:
            lines.append(
                f"corr: note — pallas row ran the kernel on "
                f"{record['corr_pallas_levels']} pyramid levels (per-level "
                "VMEM gating; partial dispatch is by design at large shapes)"
            )

    # Invariant counters from the runtime guards (bench.py train-loop row
    # under analysis/guards.py): a pipelined-loop number measured while
    # the sync-free/recompile-free invariant was VIOLATED ranks loops, not
    # kernels — flag it before anyone reads the train_loop_* fields as a
    # clean pipeline measurement. (JGL001/JGL008 audit note: this script
    # itself is pure host-side JSON analytics — no per-sample device
    # pulls to batch here; the eval-side ones are routed through the
    # inference pipeline's one-get-per-window contract.)
    transfers = record.get("train_loop_host_transfers")
    recompiles = record.get("train_loop_recompiles")
    if transfers or recompiles:
        lines.append(
            "train_loop: INVARIANT VIOLATED during the pipelined window "
            f"({transfers or 0} implicit host transfer(s), "
            f"{recompiles or 0} recompile(s)) — the train_loop_* numbers "
            "measure a stalling loop; fix the leak (see docs/ANALYSIS.md) "
            "before comparing pipeline rows"
        )

    lines.extend(_val_row_lines(record))
    lines.extend(_serve_row_lines(record))
    lines.extend(_bf16_row_lines(record))
    lines.extend(_highres_row_lines(record))
    lines.extend(_uhd_row_lines(record))
    lines.extend(_pipeline_lines(record))
    lines.extend(_earlyexit_lines(record))
    lines.extend(_fleet_lines(record))
    lines.extend(_elasticity_lines(record))
    lines.extend(_telemetry_lines(record))

    nc = record.get("pairs_per_sec_nconv_pallas")
    fell_back = record.get("pairs_per_sec_nconv_pallas_FELL_BACK_TO_XLA")
    base = record.get("value")
    calls = str(record.get("nconv_pallas_calls", ""))
    partial = False
    if calls and "/" in calls:
        fused_n, total_n = (int(x) for x in calls.split("/"))
        partial = fused_n < total_n
    if nc and base:
        if partial:
            # A mostly-XLA measurement must not flip the default on a
            # small margin — the number's provenance is mixed.
            lines.append(
                f"nconv: pallas row only PARTIALLY fused ({calls} call "
                f"sites; {nc:.2f} vs {base:.2f} pairs/s) — do NOT flip on "
                "this row; investigate the gated-out call sites first"
            )
        elif nc >= MARGIN * base:
            lines.append(
                f"nconv: FLIP default 'xla' -> 'pallas' ({nc:.2f} vs "
                f"{base:.2f} pairs/s; edit raft_ncup_tpu/ops/nconv.py "
                "RAFT_NCUP_NCONV_IMPL default)"
            )
        else:
            lines.append(
                f"nconv: keep 'xla' (pallas {nc:.2f} vs xla {base:.2f} pairs/s)"
            )
    elif nc:
        lines.append(
            f"nconv: pallas row measured ({nc:.2f} pairs/s) but no volume "
            "baseline to compare against; keep 'xla'"
        )
    elif fell_back:
        lines.append(
            "nconv: pallas row fell back to XLA at this shape "
            f"({fell_back:.2f} pairs/s) — no fused measurement; keep 'xla'"
        )
    else:
        lines.append("nconv: no pallas row measured; keep 'xla'")
    return lines


def _val_row_lines(record: dict) -> list[str]:
    """Eval-pipeline row (bench.py ``val_*`` fields, docs/PERF.md "Eval
    pipeline") — the train-loop policy applied to validation: absent row
    → no lines (older records predate it); nonzero guard counters →
    the numbers measured a leaking loop and are unusable for pipeline
    comparisons; clean row → report the recovered stall."""
    if record.get("val_pairs_per_sec") is None:
        return []
    transfers = record.get("val_loop_host_transfers")
    recompiles = record.get("val_loop_recompiles")
    if transfers or recompiles:
        return [
            "val_loop: INVARIANT VIOLATED during the pipelined eval "
            f"window ({transfers or 0} implicit host transfer(s), "
            f"{recompiles or 0} recompile(s)) — the val_* numbers measure "
            "a leaking loop; fix it (docs/ANALYSIS.md JGL008) before "
            "reading them as a pipeline measurement"
        ]
    stall = record.get("val_stall_ms_per_pair")
    pipe_ms = record.get("val_ms_per_pair")
    if stall is None or pipe_ms is None:
        return [
            "val_loop: row incomplete (no stall bracketing); rerun bench "
            "for the full eval-pipeline row"
        ]
    if stall > 0:
        return [
            f"val_loop: pipelined eval recovers {stall:.1f} ms/pair over "
            f"the per-batch-synced loop ({pipe_ms:.1f} ms/pair pipelined; "
            "invariants clean) — keep the async eval pipeline on"
        ]
    return [
        f"val_loop: no stall recovered on this host ({stall:.1f} ms/pair; "
        "saturated-host or accelerator-absent measurement) — pipeline "
        "stays on for the invariants; judge speed on accelerator rows"
    ]


def _bf16_row_lines(record: dict) -> list[str]:
    """bf16 precision rows (bench.py ``*_bf16`` fields; docs/PRECISION.md)
    — the corr_impl flip discipline applied to the precision default:
    absent row → no lines (older records predate it); any ``*_bf16``
    guard counter nonzero → the numbers measured a leaking/recompiling
    program and are unusable; parity over the recorded budget → never
    flip, regardless of speed; clean + parity met → flip only on
    accelerator data with a >= MARGIN throughput win (CPU emulates bf16
    in software — its ordering says nothing about the MXU)."""
    bf16 = record.get("pairs_per_sec_bf16")
    if bf16 is None and not any("bf16" in k for k in record):
        return []
    # Any bf16-window guard counter, wherever 'bf16' sits in the key:
    # the forward row spells them fwd_bf16_recompiles (prefix), the
    # val/serve/stream rows val_loop_recompiles_bf16 (suffix). These
    # filters run even when the forward row is MISSING — the sub-rows
    # are measured independently (a failed forward row does not stop
    # bench's later bf16 rows), and dirty numbers without an 'unusable'
    # flag are exactly the misread this function exists to prevent.
    dirty = {
        k: v
        for k, v in record.items()
        if "bf16" in k
        and ("recompiles" in k or "host_transfers" in k)
        and v
    }
    if dirty:
        return [
            "bf16: INVARIANT VIOLATED during bf16 window(s) "
            f"({dirty}) — the *_bf16 numbers measure a leaking or "
            "recompiling program; fix the leak (docs/ANALYSIS.md) "
            "before reading them, and do NOT flip the precision default"
        ]
    failed = {
        k: v
        for k, v in record.items()
        if "bf16" in k and "errors" in k and v
    }
    if failed:
        return [
            f"bf16: window(s) ERRORED ({failed}) — the *_bf16 numbers "
            "cover a partial sample; fix the failure and rerun bench "
            "before judging the precision default"
        ]
    if bf16 is None:
        return [
            "bf16: forward row missing (other *_bf16 rows recorded, "
            "invariants clean); rerun bench for the bf16 forward row — "
            "no parity measurement, no flip verdict"
        ]
    parity = record.get("bf16_forward_epe_vs_f32")
    budget = record.get("bf16_epe_budget")
    if parity is None or budget is None:
        return [
            "bf16: row incomplete (no parity measurement); rerun bench "
            "for the bf16 forward row before judging the precision "
            "default"
        ]
    if parity > budget:
        return [
            f"bf16: parity budget EXCEEDED ({parity:.4f} px EPE vs f32, "
            f"budget {budget:.4f}) — do NOT flip the precision default; "
            "investigate the drift (docs/PRECISION.md error-budget "
            "methodology) before trusting bf16 numbers"
        ]
    base = record.get("value")
    key = str(record.get("baseline_key", ""))
    on_accel = bool(key) and not key.startswith("cpu")
    if on_accel and base and bf16 >= MARGIN * base:
        return [
            f"precision: FLIP default 'f32' -> 'bf16_infer' "
            f"({bf16:.2f} vs {base:.2f} pairs/s, parity {parity:.4f} px "
            f"within budget {budget:.4f}, invariants clean; edit "
            "raft_ncup_tpu/config.py ModelConfig.precision — and retest "
            "bf16_train before flipping the training default)"
        ]
    if on_accel:
        return [
            f"bf16: parity within budget ({parity:.4f} px) but no >= "
            f"{MARGIN:.2f}x win ({bf16:.2f} vs {base or 0:.2f} pairs/s); "
            "keep precision 'f32'"
        ]
    return [
        f"bf16: parity within budget ({parity:.4f} px, invariants "
        f"clean) on a CPU row ({bf16:.2f} vs {base or 0:.2f} pairs/s, "
        "bf16 emulated) — no flip from CPU data; rows are staged for "
        "first hardware contact"
    ]


def _highres_row_lines(record: dict) -> list[str]:
    """Spatially-sharded 1080p row (bench.py ``highres_*`` fields;
    docs/SHARDING.md) — the corr_impl flip discipline applied to the
    serving/streaming mesh default: absent row → no lines (older
    records predate it); nonzero guard counters → the numbers measured
    a leaking/recompiling program and are unusable; a clean
    multi-device window with a >= MARGIN win over its own
    single-device comparison, on ACCELERATOR data → flip the
    serve/stream default mesh (CPU emulates the mesh on virtual host
    devices — its ordering says nothing about ICI collectives)."""
    hr = record.get("highres_pairs_per_sec")
    if hr is None:
        return []
    transfers = record.get("highres_host_transfers")
    recompiles = record.get("highres_recompiles")
    if transfers or recompiles:
        return [
            "highres: INVARIANT VIOLATED during the 1080p window(s) "
            f"({transfers or 0} implicit host transfer(s), "
            f"{recompiles or 0} recompile(s)) — the highres_* numbers "
            "measure a leaking or recompiling program; fix the leak "
            "(docs/ANALYSIS.md) before reading them or judging the mesh"
        ]
    devices = record.get("highres_devices") or 1
    mesh = record.get("highres_mesh", "nomesh")
    if devices <= 1:
        return [
            f"highres: single-device row ({hr:.3f} pairs/s at "
            f"{record.get('highres_iters', '?')} iters, invariants "
            "clean) — no mesh to judge; rerun with >1 visible device "
            "(--mesh) for the sharded row"
        ]
    ref = record.get("highres_pairs_per_sec_unsharded")
    if ref is None:
        return [
            f"highres: sharded row clean ({hr:.3f} pairs/s on {mesh}) "
            "but no single-device comparison in the record "
            "(BENCH_HIGHRES_COMPARE=0?); no mesh verdict without it"
        ]
    key = str(record.get("baseline_key", ""))
    on_accel = bool(key) and not key.startswith("cpu")
    if on_accel and ref and hr >= MARGIN * ref:
        return [
            f"highres: FLIP serve/stream default mesh — {mesh} measured "
            f"{hr:.3f} vs {ref:.3f} pairs/s single-device at 1080p "
            "(invariants clean; set ServeConfig.mesh / StreamConfig.mesh "
            "in raft_ncup_tpu/config.py, or --mesh on serve.py)"
        ]
    if on_accel:
        return [
            f"highres: mesh {mesh} shows no >= {MARGIN:.2f}x win at "
            f"1080p ({hr:.3f} vs {ref:.3f} pairs/s single-device); keep "
            "the unsharded default — sharding still buys per-device "
            f"memory ({record.get('highres_analysis_temp_gib', '?')} vs "
            f"{record.get('highres_analysis_temp_gib_unsharded', '?')} "
            "GiB temp)"
        ]
    return [
        f"highres: sharded row clean on CPU-emulated {mesh} "
        f"({hr:.3f} vs {ref:.3f} pairs/s single-device; per-device temp "
        f"{record.get('highres_analysis_temp_gib', '?')} vs "
        f"{record.get('highres_analysis_temp_gib_unsharded', '?')} GiB) "
        "— no mesh flip from CPU data; the row is staged for first "
        "hardware contact"
    ]


def _uhd_row_lines(record: dict) -> list[str]:
    """UHD/4K row (bench.py ``uhd_*`` fields; docs/PERF.md "Banded
    dispatch") — the corr-tier discipline at the shape the banded
    kernel exists for: absent row → no lines (older records predate
    it); dirty-or-missing guard counters → the window is unusable;
    CPU → staged, never a flip (a CPU 4K window runs the XLA fallback
    at reduced iters — it proves servability, not kernel ordering);
    clean accelerator → the corr-tier verdict (which tier carried the
    levels, and whether corr_impl='pallas' is the 4K candidate)."""
    uhd = record.get("uhd_pairs_per_sec")
    if uhd is None:
        return []
    transfers = record.get("uhd_host_transfers")
    recompiles = record.get("uhd_recompiles")
    if transfers or recompiles or transfers is None or recompiles is None:
        return [
            "uhd: INVARIANT VIOLATED (or unrecorded) during the 4K "
            f"window ({transfers if transfers is not None else '?'} "
            "implicit host transfer(s), "
            f"{recompiles if recompiles is not None else '?'} "
            "recompile(s)) — the uhd_* numbers are unusable; fix the "
            "leak (docs/ANALYSIS.md) before reading them"
        ]
    impl = record.get("uhd_corr_impl", "?")
    shape = record.get("uhd_shape", "?")
    knobs = (
        f"row_chunk={record.get('uhd_corr_row_chunk', '?')}, "
        f"query_block={record.get('uhd_corr_query_block', '?')}, "
        f"band_rows={record.get('uhd_corr_band_rows', '?')}"
    )
    key = str(record.get("baseline_key", ""))
    on_accel = bool(key) and not key.startswith("cpu")
    if not on_accel:
        return [
            f"uhd: 4K window clean on CPU ({uhd:.4f} pairs/s at "
            f"{shape}/{record.get('uhd_iters', '?')}it via "
            f"'{impl}'; {knobs}) — proves 4K is servable, says nothing "
            "about kernel ordering; the corr-tier verdict is staged "
            "for first hardware contact"
        ]
    dispatch = record.get("uhd_corr_dispatch") or {}
    if impl == "pallas" and dispatch:
        fb = dispatch.get("fallback", 0)
        if fb:
            return [
                f"uhd: pallas window clean ({uhd:.3f} pairs/s) but "
                f"{fb}/{dispatch.get('levels_total', '?')} pyramid "
                "level(s) still fell back to XLA — tune the band knobs "
                f"({knobs}; RAFT_NCUP_CORR_BAND_ROWS/"
                "RAFT_NCUP_CORR_QUERY_BLOCK) before judging the 4K tier"
            ]
        return [
            f"uhd: 4K corr tier VERDICT — '{impl}' carried every level "
            f"on-kernel (resident {dispatch.get('kernel', 0)} + banded "
            f"{dispatch.get('banded', 0)}; {uhd:.3f} pairs/s, "
            f"invariants clean, {knobs}); corr_impl='pallas' is the 4K "
            "default candidate — compare an onthefly rerun "
            "(BENCH_UHD_CORR=onthefly) before flipping "
            "ModelConfig.corr_impl for UHD serving"
        ]
    return [
        f"uhd: accelerator window clean via '{impl}' ({uhd:.3f} "
        f"pairs/s at {shape}; {knobs}) — rerun with "
        "BENCH_UHD_CORR=pallas for the kernel-tier comparison before "
        "any corr verdict"
    ]


def _pipeline_lines(record: dict) -> list[str]:
    """Iteration-pipeline row (bench.py ``pipeline_*`` fields;
    docs/SHARDING.md "Pipeline axis") — whether the pipe-axis streaming
    schedule earns its mesh: absent row → no lines (older records
    predate it); dirty-or-missing guard counters → the stream is
    unusable; S=1 → the delegation path, nothing to judge; CPU →
    staged, never a flip (virtual pipeline stages share one host — the
    S× claim is unmeasurable, only the invariants and the
    collective-permute fingerprint carry); clean accelerator → the
    pipeline-vs-monolithic verdict at MARGIN."""
    pps = record.get("pipeline_pairs_per_sec")
    if pps is None:
        return []
    transfers = record.get("pipeline_host_transfers")
    recompiles = record.get("pipeline_recompiles")
    if transfers or recompiles or transfers is None or recompiles is None:
        return [
            "pipeline: INVARIANT VIOLATED (or unrecorded) during the "
            "streaming window "
            f"({transfers if transfers is not None else '?'} implicit "
            "host transfer(s), "
            f"{recompiles if recompiles is not None else '?'} "
            "recompile(s)) — the pipeline_* numbers measure a stalling "
            "stream; fix the leak (docs/ANALYSIS.md) before reading them"
        ]
    segs = record.get("pipeline_segments", "?")
    shape = record.get("pipeline_shape", "?")
    perm = record.get("pipeline_collective_permutes")
    if segs == 1:
        return [
            f"pipeline: single-stage record ({pps:.4f} pairs/s at "
            f"{shape} via the monolithic delegation path) — no pipe "
            "mesh on this host; rerun with >1 visible device (or "
            "BENCH_PIPELINE_SEGMENTS) for a pipeline measurement"
        ]
    handoff = (
        f"{perm} collective-permute(s)/tick"
        if perm is not None
        else "handoff fingerprint unrecorded"
    )
    key = str(record.get("baseline_key", ""))
    on_accel = bool(key) and not key.startswith("cpu")
    if not on_accel:
        return [
            f"pipeline: S={segs} stream clean on CPU ({pps:.4f} "
            f"pairs/s at {shape}/"
            f"{record.get('pipeline_iters', '?')}it, "
            f"{record.get('pipeline_micro_batches', '?')} micro-"
            f"batches, {handoff}, invariants clean) — virtual stages "
            "share one host, so this proves schedule correctness, not "
            "throughput; the pipeline-vs-monolithic verdict is staged "
            "for first hardware contact"
        ]
    mono = record.get("pipeline_pairs_per_sec_monolithic")
    if not mono:
        return [
            f"pipeline: S={segs} accelerator stream clean ({pps:.3f} "
            f"pairs/s, {handoff}) but no monolithic comparison window "
            "in the record — rerun without BENCH_PIPELINE_COMPARE=0 "
            "before any verdict"
        ]
    if pps >= MARGIN * mono:
        return [
            f"pipeline: VERDICT — S={segs} streaming beats the "
            f"monolithic scan ({pps:.3f} vs {mono:.3f} pairs/s at "
            f"{shape}; {handoff}; per-segment "
            f"{record.get('pipeline_flops_per_segment', '?')} flops); "
            "adopt the pipe mesh for streaming inference (ServeConfig "
            "mesh=(1,1,S)) and sweep S per ROADMAP item 1's chip-window "
            "checklist"
        ]
    return [
        f"pipeline: keep the monolithic scan — S={segs} streaming "
        f"({pps:.3f} pairs/s) does not clear the monolithic window "
        f"({mono:.3f} pairs/s) by the {MARGIN}x margin; the handoff "
        f"cost ({handoff}) is not yet paying for itself at this "
        "shape/iters"
    ]


def _earlyexit_lines(record: dict) -> list[str]:
    """Early-exit row (bench.py ``earlyexit_*`` fields; docs/PERF.md
    "Early exit") — the one speedup verdict this script WILL issue from
    CPU data: the measured win is a FLOP cut (fewer while_loop trips),
    honest on every backend, unlike kernel ordering or mesh claims.
    Absent row → no lines (older records predate it); dirty-or-missing
    guard counters → the windows are unusable (a recompile means the
    tolerance leaked into shapes; a transfer means convergence was
    inspected on the host); EPE over the pinned budget → never enable,
    regardless of speed; within budget + >= MARGIN throughput win over
    the full-budget twin → recommend enabling the knob."""
    pps = record.get("earlyexit_pairs_per_sec")
    if pps is None:
        return []
    transfers = record.get("earlyexit_host_transfers")
    recompiles = record.get("earlyexit_recompiles")
    if transfers or recompiles or transfers is None or recompiles is None:
        return [
            "earlyexit: INVARIANT VIOLATED (or unrecorded) during the "
            "adaptive-compute window(s) "
            f"({transfers if transfers is not None else '?'} implicit "
            "host transfer(s), "
            f"{recompiles if recompiles is not None else '?'} "
            "recompile(s)) — detection must live in-graph with a closed "
            "executable set; the earlyexit_* numbers are unusable until "
            "the leak is fixed (docs/ANALYSIS.md)"
        ]
    full = record.get("earlyexit_pairs_per_sec_fullbudget")
    epe = record.get("earlyexit_epe_vs_full")
    budget = record.get("earlyexit_epe_budget")
    if not full or epe is None or budget is None:
        return [
            "earlyexit: row incomplete (no full-budget twin or parity "
            "measurement); rerun bench for the full early-exit row "
            "before judging the knob"
        ]
    tol = record.get("earlyexit_tol", "?")
    execd = record.get("earlyexit_iters_executed_mean", "?")
    budgeted = record.get("earlyexit_iters_budgeted", "?")
    if epe > budget:
        return [
            f"earlyexit: quality budget EXCEEDED ({epe:.4f} px EPE vs "
            f"the full-budget twin, budget {budget:.4f}, tol={tol}) — "
            "do NOT enable RAFT_NCUP_EARLYEXIT at this tolerance; "
            "tighten RAFT_NCUP_EARLYEXIT_TOL and rerun bench"
        ]
    if pps >= MARGIN * full:
        return [
            f"earlyexit: VERDICT — enable RAFT_NCUP_EARLYEXIT=1 "
            f"(RAFT_NCUP_EARLYEXIT_TOL={tol}): {pps:.2f} vs {full:.2f} "
            f"pairs/s full-budget at matched quality ({epe:.4f} px EPE "
            f"within {budget:.4f}), executed {execd} of {budgeted} "
            "budgeted iters mean, invariants clean — the FLOP cut is "
            "backend-honest, so this CPU verdict carries"
        ]
    return [
        f"earlyexit: keep the knob off — {pps:.2f} vs {full:.2f} "
        f"pairs/s full-budget misses the {MARGIN}x margin (parity "
        f"{epe:.4f} px within {budget:.4f}; executed {execd} of "
        f"{budgeted} budgeted iters mean); per-call overhead is "
        "swallowing the FLOP cut at this shape mix"
    ]


def _telemetry_lines(record: dict) -> list[str]:
    """Telemetry snapshot consistency (bench.py serve/stream rows;
    docs/OBSERVABILITY.md) — absent snapshot fields → no lines (older
    records predate them); a window whose sanctioned drain-pull counter
    drifts from its dispatched-batch counter → flagged INCONSISTENT
    (the two are independent measurements of the same thing: one
    AsyncDrain pull per dispatched batch — drift means results were
    delivered outside the sanctioned path, or dropped); equal → a
    one-line consistency confirmation. The measured observer overhead
    is also judged against its 3%-of-p50 budget when recorded."""
    lines = []
    for prefix in ("serve", "stream"):
        gets = record.get(f"{prefix}_sanctioned_gets")
        batches = record.get(f"{prefix}_batches")
        if gets is None or batches is None:
            continue  # no telemetry snapshot in this record
        if gets != batches:
            lines.append(
                f"telemetry: {prefix} snapshot INCONSISTENT — "
                f"{gets} sanctioned drain pull(s) vs {batches} dispatched "
                "batch(es) in the window; every batch's results must "
                "ride exactly one sanctioned AsyncDrain device_get, so "
                f"the drift means the {prefix}_* numbers cover deliveries "
                "outside the sanctioned path (or dropped batches) — "
                "explain it (docs/OBSERVABILITY.md) before reading them"
            )
        else:
            lines.append(
                f"telemetry: {prefix} snapshot consistent "
                f"({gets} sanctioned pull(s) = {batches} batch(es))"
            )
    overhead = record.get("serve_telemetry_overhead_pct")
    if overhead is not None and overhead > 3.0:
        lines.append(
            f"telemetry: serve tracing overhead {overhead:.1f}% of p50 "
            "EXCEEDS the 3% budget (docs/OBSERVABILITY.md methodology) — "
            "profile the tracer hot path before keeping tracing-on "
            "defaults"
        )
    lines.extend(_slo_lines(record))
    return lines


def _slo_lines(record: dict) -> list[str]:
    """Health/SLO verdict block (bench.py serve/stream rows;
    docs/OBSERVABILITY.md "SLO burn rate") — absent block → no lines
    (older records predate it); a window whose health ended DEGRADED
    (or worse) or that paged an SLO → flagged: the latencies were
    measured while the budget controller was coarsening responses, so
    they describe a degraded service, not the steady state every other
    verdict assumes; clean → one confirmation line naming the verdict
    count."""
    lines = []
    for prefix in ("serve", "stream"):
        health = record.get(f"{prefix}_health")
        verdicts = record.get(f"{prefix}_slo")
        if health is None and verdicts is None:
            continue  # no health/SLO block in this record
        pages = record.get(f"{prefix}_slo_pages") or 0
        paging = sorted(
            name for name, v in (verdicts or {}).items() if v.get("page")
        )
        if health not in (None, "ready") or pages or paging:
            detail = []
            if health not in (None, "ready"):
                detail.append(f"health={health}")
            if pages:
                detail.append(f"{pages} page(s)")
            if paging:
                detail.append("paging: " + ", ".join(paging))
            lines.append(
                f"slo: {prefix} window DEGRADED ({'; '.join(detail)}) — "
                f"the {prefix}_* latencies include coarsened (degraded-"
                "budget) responses; fix the burn or lower the load and "
                "rerun bench before reading them as steady state"
            )
        else:
            lines.append(
                f"slo: {prefix} window clean (health=ready, 0 pages "
                f"over {len(verdicts or {})} declared SLO(s))"
            )
    return lines


def _fleet_lines(record: dict) -> list[str]:
    """Fleet row (bench.py ``fleet_*`` fields; docs/FLEET.md) — the
    serve-row policy applied per replica: absent row → no lines (older
    records predate the fleet tier); any replica's guard counters
    nonzero → the whole row is unusable (one leaking replica poisons
    the fleet percentiles); sheds/errors/failovers or a drain-contract
    violation → the row measured robustness machinery, not service;
    clean → the router-hop verdict against the single-replica serve
    row, with per-replica occupancy."""
    if record.get("fleet_pairs_per_sec") is None:
        return []
    recompiles = record.get("fleet_replica_recompiles") or []
    transfers = record.get("fleet_replica_host_transfers") or []
    dirty = [
        i for i, (r, t) in enumerate(zip(recompiles, transfers))
        if (r is None or r) or (t is None or t)
    ]
    if dirty:
        return [
            "fleet: INVARIANT VIOLATED on replica(s) "
            f"{dirty} (per-replica recompiles {recompiles}, implicit "
            f"host transfers {transfers}; None = report missing) — the "
            "fleet_* latencies include a leaking or recompiling "
            "replica; fix it (docs/FLEET.md) before reading them"
        ]
    shed = record.get("fleet_shed") or 0
    errors = record.get("fleet_errors") or 0
    failovers = record.get("fleet_failovers") or 0
    deaths = record.get("fleet_deaths") or 0
    violations = record.get("fleet_contract_violations") or []
    # Any response that is not ok shrank the latency sample: timeouts/
    # rejections count against steady state exactly like sheds, and a
    # row whose ok count is short of its request count is lossy even if
    # every per-status field reads 0 (belt and suspenders).
    timeouts = record.get("fleet_timeouts") or 0
    rejected = record.get("fleet_rejected") or 0
    n_req = record.get("fleet_requests")
    n_ok = record.get("fleet_ok")
    lossy = (
        n_req is not None and n_ok is not None and n_ok < n_req
    )
    if (shed or errors or failovers or deaths or violations
            or timeouts or rejected or lossy):
        return [
            f"fleet: window NOT steady state ({shed} shed, {errors} "
            f"error(s), {timeouts} timeout(s), {rejected} rejected, "
            f"{failovers} failover(s), {deaths} replica "
            f"death(s), {len(violations)} drain-contract violation(s); "
            f"ok {n_ok}/{n_req}) "
            "— the fleet_* numbers measured the robustness machinery, "
            "not service; rerun bench on a healthy fleet"
        ]
    p50 = record.get("fleet_p50_ms")
    p99 = record.get("fleet_p99_ms")
    if p50 is None or p99 is None:
        return [
            "fleet: row incomplete (no latency percentiles); rerun "
            "bench for the full fleet row"
        ]
    serve_p50 = record.get("serve_p50_ms")
    hop = (
        f"; router hop vs single-replica serve row: "
        f"{p50 - serve_p50:+.1f} ms of p50"
        if serve_p50 is not None else
        "; no serve row in this record to compare the router hop against"
    )
    occ = record.get("fleet_per_replica_completed")
    lines = [
        f"fleet: steady state {record['fleet_pairs_per_sec']:.2f} "
        f"pairs/s over {record.get('fleet_replicas', '?')} replicas, "
        f"p50 {p50:.1f} ms / p99 {p99:.1f} ms "
        f"(per-replica guard counters all 0; occupancy {occ}){hop}"
    ]
    # Fleet telemetry overhead (bench's on/off window over the SAME
    # warm fleet, router + replica hubs toggled over the wire): the
    # serve row's 3% observer budget applied at fleet granularity.
    overhead = record.get("fleet_telemetry_overhead_pct")
    if overhead is not None:
        if overhead > 3.0:
            lines.append(
                f"fleet telemetry: tracing overhead {overhead:.1f}% of "
                "p50 EXCEEDS the 3% budget "
                f"(p50 {p50:.1f} ms on vs "
                f"{record.get('fleet_p50_ms_notelemetry')} ms off) — "
                "profile the fleet producer paths before trusting the "
                "fleet latencies (docs/OBSERVABILITY.md)"
            )
        else:
            lines.append(
                f"fleet telemetry: measured overhead {overhead:.1f}% of "
                "p50 (within the 3% budget)"
            )
    return lines


def _elasticity_lines(record: dict) -> list[str]:
    """Elasticity row (bench.py ``elasticity_*`` fields; docs/FLEET.md
    "Elasticity bench") — the fleet-row policy INVERTED: that row must
    measure service (any shed disqualifies it), this row must measure
    the machinery. Absent row → no lines (older records predate the
    autoscaler); any in-flight loss, drain-contract violation, or open
    breaker → the cycle is UNSAFE and nothing else about the row
    matters; a leaking replica → the latencies are unusable; otherwise
    the verdict is whether the elastic cycle CLOSED — the load step
    forced a scale-up, the capacity reached READY, and the post-burst
    calm gave it back — with the warmup-window sheds carrying
    ETA-floored (not treadmill-default) retry hints."""
    n_req = record.get("elasticity_requests")
    if n_req is None:
        return []
    losses = record.get("elasticity_losses") or 0
    violations = record.get("elasticity_contract_violations") or []
    breaker = record.get("elasticity_breaker_open")
    if losses or violations or breaker:
        detail = []
        if losses:
            detail.append(f"{losses} lost in-flight response(s)")
        if violations:
            detail.append(
                f"{len(violations)} drain-contract violation(s): "
                f"{violations}"
            )
        if breaker:
            detail.append(
                "autoscaler breaker OPEN (consecutive failed scale-ups)"
            )
        return [
            f"elasticity: cycle UNSAFE ({'; '.join(detail)}) — elastic "
            "scaling may NOT be enabled on this build; fix the loss "
            "path (docs/FLEET.md drain contract) and rerun bench"
        ]
    recompiles = record.get("elasticity_replica_recompiles") or []
    transfers = record.get("elasticity_replica_host_transfers") or []
    dirty = [
        i for i, (r, t) in enumerate(zip(recompiles, transfers))
        if (r is None or r) or (t is None or t)
    ]
    if dirty:
        return [
            "elasticity: INVARIANT VIOLATED on serving replica(s) "
            f"{dirty} (recompiles {recompiles}, implicit host transfers "
            f"{transfers}; None = report missing) — the elasticity "
            "latencies include a leaking or recompiling replica; fix it "
            "before reading them"
        ]
    ups = record.get("elasticity_scale_ups") or 0
    ups_done = record.get("elasticity_scale_ups_completed") or 0
    downs = record.get("elasticity_scale_downs") or 0
    shed = record.get("elasticity_shed") or 0
    floored = record.get("elasticity_shed_eta_floored") or 0
    ttr = record.get("elasticity_time_to_ready_s")
    lines = []
    if not ups:
        lines.append(
            f"elasticity: step never pressured the fleet (0 scale-ups "
            f"over {n_req} requests, {shed} shed) — no elasticity "
            "verdict; raise BENCH_ELASTICITY_HIGH or check the "
            "calibrated interval before reading the row"
        )
    elif ups_done < ups:
        lines.append(
            f"elasticity: cycle OPEN — {ups - ups_done} of {ups} "
            "scale-up(s) never reached READY in the window "
            f"({record.get('elasticity_failed_scale_ups') or 0} failed) "
            "— raise BENCH_ELASTICITY_GRACE_S (spawn compile may exceed "
            "the settle window on CPU) and rerun before judging"
        )
    elif downs < ups_done:
        lines.append(
            f"elasticity: capacity never given back ({ups_done} "
            f"scale-up(s) READY after {ttr}s but only {downs} "
            "scale-down(s)) — the cooldown phase or settle window is "
            "too short for the anti-flap bounds; rerun before judging"
        )
    else:
        lines.append(
            "elasticity: cycle CLOSED — the load step scaled "
            f"{ups} up (READY in {ttr}s measured) and the calm gave "
            f"{downs} back with 0 lost in-flight responses "
            f"(ok {record.get('elasticity_ok')}/{n_req}, {shed} honest "
            f"shed(s), p50 {record.get('elasticity_p50_ms')} ms / p99 "
            f"{record.get('elasticity_p99_ms')} ms); elastic scaling "
            "holds its zero-loss contract on this build"
        )
    if shed and not floored:
        lines.append(
            f"elasticity: backpressure DISHONEST — {shed} shed(s) "
            "during the window and none carried a retry hint above the "
            "default floor; while capacity warms, sheds must quote the "
            "time-to-READY estimate (FleetRouter.set_scale_eta), not "
            "the re-shed treadmill"
        )
    return lines


def _serve_row_lines(record: dict) -> list[str]:
    """Serving row (bench.py ``serve_*`` fields; docs/SERVING.md) — the
    val-row policy applied to the serving tier: absent row → no lines
    (older records predate it); nonzero guard counters → the latencies
    measured a leaking/recompiling server and are unusable; a window
    that shed or timed out → it measured backpressure, not service;
    clean → the steady-state latency verdict the SLO reads."""
    if record.get("serve_pairs_per_sec") is None:
        return []
    transfers = record.get("serve_host_transfers")
    recompiles = record.get("serve_recompiles")
    if transfers or recompiles:
        return [
            "serve: INVARIANT VIOLATED during the serving window "
            f"({transfers or 0} implicit host transfer(s), "
            f"{recompiles or 0} recompile(s)) — the serve_* latencies "
            "measure a leaking or recompiling server; fix it "
            "(docs/SERVING.md, docs/ANALYSIS.md) before reading them "
            "as a service-time measurement"
        ]
    shed = record.get("serve_shed") or 0
    timeouts = record.get("serve_timeouts") or 0
    errors = record.get("serve_errors") or 0
    drops = record.get("serve_budget_drops") or 0
    if shed or timeouts:
        return [
            f"serve: window OVERLOADED ({shed} shed, {timeouts} "
            "timeout(s)) — the serve_* numbers measured backpressure, "
            "not steady-state service; lower the arrival rate or raise "
            "capacity and rerun bench"
        ]
    if errors:
        return [
            f"serve: window ERRORED ({errors} request(s) failed "
            "server-side) — the percentiles cover a partial sample; "
            "fix the failure and rerun bench before reading them"
        ]
    p50 = record.get("serve_p50_ms")
    p99 = record.get("serve_p99_ms")
    if p50 is None or p99 is None:
        return [
            "serve: row incomplete (no latency percentiles); rerun "
            "bench for the full serving row"
        ]
    degr = (
        f"; budget degraded {drops}x during the window (arrival rate "
        "sits near capacity — p99 includes coarser-flow responses)"
        if drops else "; budget never degraded (full-quality responses)"
    )
    n_ok = record.get("serve_ok", record.get("serve_requests", "?"))
    return [
        f"serve: steady state {record['serve_pairs_per_sec']:.2f} "
        f"pairs/s, p50 {p50:.1f} ms / p99 {p99:.1f} ms at "
        f"{record.get('serve_iters', '?')} iters over "
        f"{n_ok} requests "
        f"(invariants clean){degr}"
    ]


def main() -> None:
    src = open(sys.argv[1]) if len(sys.argv) > 1 else sys.stdin
    text = src.read().strip()
    if not text:
        print(
            "flip_recommendations: no input (bench produced no record?)",
            file=sys.stderr,
        )
        raise SystemExit(1)
    # Accept either a bare record or bench stdout whose LAST line is JSON.
    try:
        record = json.loads(text.splitlines()[-1])
    except ValueError as e:
        print(
            f"flip_recommendations: last input line is not JSON ({e})",
            file=sys.stderr,
        )
        raise SystemExit(1)
    print("kernel-default recommendations:")
    for line in recommend(record):
        print("  - " + line)


if __name__ == "__main__":
    main()
