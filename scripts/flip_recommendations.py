#!/usr/bin/env python
"""Data-driven kernel-default recommendations from a bench record.

Reads one bench.py JSON record (file argument or stdin) and prints which
implementation defaults the measurements support flipping:

- ``ModelConfig.corr_impl`` (raft_ncup_tpu/config.py) — 'volume' vs
  'onthefly' vs 'pallas' (reference hot path: core/corr.py:13-44);
- ``RAFT_NCUP_NCONV_IMPL`` (raft_ncup_tpu/ops/nconv.py) — 'xla' vs the
  fused Pallas NConv kernel.

Defaults only flip on ACCELERATOR data: CPU rows order kernels by how
well they suit a host CPU, not the MXU/VMEM tradeoffs the kernels were
built around (docs/PERF.md: volume beats onthefly on CPU at the small
shape for exactly this reason).
"""

from __future__ import annotations

import json
import sys

MARGIN = 1.03  # >=3% win required to recommend changing a default


def recommend(record: dict) -> list[str]:
    lines = []
    key = str(record.get("baseline_key", ""))
    if key.startswith("cpu") or not key:
        # Kernel defaults never flip on CPU data, but the eval-pipeline
        # row's invariant verdict still matters (a leaking loop is a
        # leaking loop on any backend).
        return [
            "no accelerator measurement in this record "
            f"(baseline_key={key or 'absent'!r}); defaults stay "
            "corr_impl='volume', RAFT_NCUP_NCONV_IMPL='xla' pending TPU data"
        ] + _val_row_lines(record) + _serve_row_lines(record)

    corr = {"volume": record.get("value")}
    for tag in ("onthefly", "pallas"):
        v = record.get(f"pairs_per_sec_{tag}")
        if v:
            corr[tag] = v
    corr = {k: v for k, v in corr.items() if v}
    if not corr or "volume" not in corr:
        # Without the volume row there is no corr comparison: a
        # watchdog-killed primary attempt can leave only variant rows (or
        # none), and flipping on variant-vs-variant data would change the
        # default with no baseline evidence (ADVICE r5). The nconv section
        # below still runs — its fell-back diagnosis needs no baseline.
        lines.append(
            "corr_impl: no volume baseline in record "
            f"(measured: {sorted(corr) or 'none'}); defaults stay — "
            "rerun bench for the primary row"
        )
    else:
        best = max(corr, key=corr.get)
        if len(corr) < 2:
            lines.append(
                f"corr_impl: only {list(corr)} measured; no comparison possible"
            )
        elif best != "volume" and corr[best] >= MARGIN * corr.get("volume", 0):
            lines.append(
                f"corr_impl: FLIP default 'volume' -> '{best}' "
                f"({corr[best]:.2f} vs {corr['volume']:.2f} pairs/s; "
                "edit raft_ncup_tpu/config.py ModelConfig.corr_impl)"
            )
        else:
            lines.append(
                f"corr_impl: keep 'volume' ({ {k: round(v, 2) for k, v in corr.items()} })"
            )

        if "corr_pallas_levels" in record and "pallas" in corr:
            lines.append(
                f"corr: note — pallas row ran the kernel on "
                f"{record['corr_pallas_levels']} pyramid levels (per-level "
                "VMEM gating; partial dispatch is by design at large shapes)"
            )

    # Invariant counters from the runtime guards (bench.py train-loop row
    # under analysis/guards.py): a pipelined-loop number measured while
    # the sync-free/recompile-free invariant was VIOLATED ranks loops, not
    # kernels — flag it before anyone reads the train_loop_* fields as a
    # clean pipeline measurement. (JGL001/JGL008 audit note: this script
    # itself is pure host-side JSON analytics — no per-sample device
    # pulls to batch here; the eval-side ones are routed through the
    # inference pipeline's one-get-per-window contract.)
    transfers = record.get("train_loop_host_transfers")
    recompiles = record.get("train_loop_recompiles")
    if transfers or recompiles:
        lines.append(
            "train_loop: INVARIANT VIOLATED during the pipelined window "
            f"({transfers or 0} implicit host transfer(s), "
            f"{recompiles or 0} recompile(s)) — the train_loop_* numbers "
            "measure a stalling loop; fix the leak (see docs/ANALYSIS.md) "
            "before comparing pipeline rows"
        )

    lines.extend(_val_row_lines(record))
    lines.extend(_serve_row_lines(record))

    nc = record.get("pairs_per_sec_nconv_pallas")
    fell_back = record.get("pairs_per_sec_nconv_pallas_FELL_BACK_TO_XLA")
    base = record.get("value")
    calls = str(record.get("nconv_pallas_calls", ""))
    partial = False
    if calls and "/" in calls:
        fused_n, total_n = (int(x) for x in calls.split("/"))
        partial = fused_n < total_n
    if nc and base:
        if partial:
            # A mostly-XLA measurement must not flip the default on a
            # small margin — the number's provenance is mixed.
            lines.append(
                f"nconv: pallas row only PARTIALLY fused ({calls} call "
                f"sites; {nc:.2f} vs {base:.2f} pairs/s) — do NOT flip on "
                "this row; investigate the gated-out call sites first"
            )
        elif nc >= MARGIN * base:
            lines.append(
                f"nconv: FLIP default 'xla' -> 'pallas' ({nc:.2f} vs "
                f"{base:.2f} pairs/s; edit raft_ncup_tpu/ops/nconv.py "
                "RAFT_NCUP_NCONV_IMPL default)"
            )
        else:
            lines.append(
                f"nconv: keep 'xla' (pallas {nc:.2f} vs xla {base:.2f} pairs/s)"
            )
    elif nc:
        lines.append(
            f"nconv: pallas row measured ({nc:.2f} pairs/s) but no volume "
            "baseline to compare against; keep 'xla'"
        )
    elif fell_back:
        lines.append(
            "nconv: pallas row fell back to XLA at this shape "
            f"({fell_back:.2f} pairs/s) — no fused measurement; keep 'xla'"
        )
    else:
        lines.append("nconv: no pallas row measured; keep 'xla'")
    return lines


def _val_row_lines(record: dict) -> list[str]:
    """Eval-pipeline row (bench.py ``val_*`` fields, docs/PERF.md "Eval
    pipeline") — the train-loop policy applied to validation: absent row
    → no lines (older records predate it); nonzero guard counters →
    the numbers measured a leaking loop and are unusable for pipeline
    comparisons; clean row → report the recovered stall."""
    if record.get("val_pairs_per_sec") is None:
        return []
    transfers = record.get("val_loop_host_transfers")
    recompiles = record.get("val_loop_recompiles")
    if transfers or recompiles:
        return [
            "val_loop: INVARIANT VIOLATED during the pipelined eval "
            f"window ({transfers or 0} implicit host transfer(s), "
            f"{recompiles or 0} recompile(s)) — the val_* numbers measure "
            "a leaking loop; fix it (docs/ANALYSIS.md JGL008) before "
            "reading them as a pipeline measurement"
        ]
    stall = record.get("val_stall_ms_per_pair")
    pipe_ms = record.get("val_ms_per_pair")
    if stall is None or pipe_ms is None:
        return [
            "val_loop: row incomplete (no stall bracketing); rerun bench "
            "for the full eval-pipeline row"
        ]
    if stall > 0:
        return [
            f"val_loop: pipelined eval recovers {stall:.1f} ms/pair over "
            f"the per-batch-synced loop ({pipe_ms:.1f} ms/pair pipelined; "
            "invariants clean) — keep the async eval pipeline on"
        ]
    return [
        f"val_loop: no stall recovered on this host ({stall:.1f} ms/pair; "
        "saturated-host or accelerator-absent measurement) — pipeline "
        "stays on for the invariants; judge speed on accelerator rows"
    ]


def _serve_row_lines(record: dict) -> list[str]:
    """Serving row (bench.py ``serve_*`` fields; docs/SERVING.md) — the
    val-row policy applied to the serving tier: absent row → no lines
    (older records predate it); nonzero guard counters → the latencies
    measured a leaking/recompiling server and are unusable; a window
    that shed or timed out → it measured backpressure, not service;
    clean → the steady-state latency verdict the SLO reads."""
    if record.get("serve_pairs_per_sec") is None:
        return []
    transfers = record.get("serve_host_transfers")
    recompiles = record.get("serve_recompiles")
    if transfers or recompiles:
        return [
            "serve: INVARIANT VIOLATED during the serving window "
            f"({transfers or 0} implicit host transfer(s), "
            f"{recompiles or 0} recompile(s)) — the serve_* latencies "
            "measure a leaking or recompiling server; fix it "
            "(docs/SERVING.md, docs/ANALYSIS.md) before reading them "
            "as a service-time measurement"
        ]
    shed = record.get("serve_shed") or 0
    timeouts = record.get("serve_timeouts") or 0
    errors = record.get("serve_errors") or 0
    drops = record.get("serve_budget_drops") or 0
    if shed or timeouts:
        return [
            f"serve: window OVERLOADED ({shed} shed, {timeouts} "
            "timeout(s)) — the serve_* numbers measured backpressure, "
            "not steady-state service; lower the arrival rate or raise "
            "capacity and rerun bench"
        ]
    if errors:
        return [
            f"serve: window ERRORED ({errors} request(s) failed "
            "server-side) — the percentiles cover a partial sample; "
            "fix the failure and rerun bench before reading them"
        ]
    p50 = record.get("serve_p50_ms")
    p99 = record.get("serve_p99_ms")
    if p50 is None or p99 is None:
        return [
            "serve: row incomplete (no latency percentiles); rerun "
            "bench for the full serving row"
        ]
    degr = (
        f"; budget degraded {drops}x during the window (arrival rate "
        "sits near capacity — p99 includes coarser-flow responses)"
        if drops else "; budget never degraded (full-quality responses)"
    )
    n_ok = record.get("serve_ok", record.get("serve_requests", "?"))
    return [
        f"serve: steady state {record['serve_pairs_per_sec']:.2f} "
        f"pairs/s, p50 {p50:.1f} ms / p99 {p99:.1f} ms at "
        f"{record.get('serve_iters', '?')} iters over "
        f"{n_ok} requests "
        f"(invariants clean){degr}"
    ]


def main() -> None:
    src = open(sys.argv[1]) if len(sys.argv) > 1 else sys.stdin
    text = src.read().strip()
    if not text:
        print(
            "flip_recommendations: no input (bench produced no record?)",
            file=sys.stderr,
        )
        raise SystemExit(1)
    # Accept either a bare record or bench stdout whose LAST line is JSON.
    try:
        record = json.loads(text.splitlines()[-1])
    except ValueError as e:
        print(
            f"flip_recommendations: last input line is not JSON ({e})",
            file=sys.stderr,
        )
        raise SystemExit(1)
    print("kernel-default recommendations:")
    for line in recommend(record):
        print("  - " + line)


if __name__ == "__main__":
    main()
