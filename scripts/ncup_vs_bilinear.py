#!/usr/bin/env python
"""Twin experiment: NCUP vs bilinear upsampling on discontinuity-rich data.

The paper's central claim is that normalized-convolution guided
upsampling refines flow at motion boundaries better than naive
interpolation (reference: core/upsampler.py:75-210, README.md:11). No
real dataset ships in this environment, so this script builds the
strongest data-free version of that test (VERDICT r4 #2):

1. Train a RAFT-small trunk on the piecewise-rigid procedural split
   (sharp flow boundaries + occlusion, `--synthetic_style rigid`).
2. Train ONE twin on top of that exact frozen trunk: raft_nc_dbl with
   the NCUP upsampler (`--freeze_raft --load_pretrained`), the
   reference's flagship stage-2 workflow (train_raft_nc_things.sh:22).
3. Evaluate BOTH twins — the trained NCUP head and the parameter-free
   bilinear head — on the held-out rigid split with the boundary-band
   EPE metric. The trunk (and therefore the 1/8-resolution flow being
   upsampled) is bit-identical between the twins, so any delta is
   attributable to the upsampler alone.

Error bars (ROADMAP carry-over): the held-out evaluation runs once per
split seed (``--eval_seeds``, default 3 seeds), giving per-seed
boundary-band deltas, and :func:`bootstrap_ci` puts a percentile
bootstrap CI on their mean — the quality claim ships with its
uncertainty instead of a single draw of the synthetic split.

Re-runnable: finished stages are skipped (presence of the final
checkpoint step), so a crashed run resumes where it left off.
Emits docs/ncup_vs_bilinear.json and a markdown table on stdout.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def bootstrap_ci(
    values: list[float],
    n_resamples: int = 10_000,
    seed: int = 0,
    alpha: float = 0.05,
) -> dict:
    """Percentile bootstrap CI for the mean of ``values``.

    Deterministic given ``seed``. With few seeds (the 3-seed default)
    the interval is coarse by construction — it honestly reflects how
    little the seed dimension has been sampled, which is the point:
    a claim whose CI straddles zero hasn't been established.
    """
    vals = np.asarray(values, np.float64)
    if vals.size == 0:
        raise ValueError("bootstrap_ci needs at least one value")
    rng = np.random.default_rng(seed)
    idx = rng.integers(0, vals.size, size=(int(n_resamples), vals.size))
    means = vals[idx].mean(axis=1)
    lo, hi = np.quantile(means, [alpha / 2.0, 1.0 - alpha / 2.0])
    return {
        "mean": float(vals.mean()),
        "ci_lo": float(lo),
        "ci_hi": float(hi),
        "alpha": alpha,
        "n_values": int(vals.size),
        "n_resamples": int(n_resamples),
    }


def sh(args: list[str]) -> None:
    print("+ " + " ".join(args), flush=True)
    subprocess.run(args, check=True, cwd=REPO)


def train_argv(a: argparse.Namespace, twin: str) -> list[str]:
    """argv for train.py; also re-parsed at eval time so the evaluated
    ModelConfig is exactly the trained one."""
    if twin not in ("trunk", "ncup", "bilinear"):
        raise ValueError(f"unknown twin: {twin!r}")
    common = [
        "--stage", "chairs", "--small",
        "--synthetic_ok", "--synthetic_style", "rigid",
        "--platform", "cpu",
        "--image_size", "64", "96", "--batch_size", "2", "--iters", "4",
        "--wdecay", "1e-5", "--validation", "synthetic_rigid",
        "--checkpoint_dir", a.ckpt_dir, "--seed", str(a.seed),
    ]
    if twin == "trunk":
        return [
            "--name", a.trunk_name, "--model", "raft",
            "--num_steps", str(a.trunk_steps), "--lr", "4e-4",
            "--val_freq", "400", "--sum_freq", "100",
        ] + common
    argv = [
        "--name", a.ncup_name, "--model", "raft_nc_dbl",
        "--freeze_raft",
        "--load_pretrained", os.path.join(a.ckpt_dir, a.trunk_name),
        "--num_steps", str(a.ncup_steps), "--lr", "2e-4",
        "--val_freq", "250", "--sum_freq", "100",
    ] + common
    if twin == "bilinear":
        argv.append("--upsampler_bi")
    return argv


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--trunk_steps", type=int, default=4000)
    p.add_argument("--ncup_steps", type=int, default=2000)
    p.add_argument("--seed", type=int, default=1234)
    p.add_argument("--ckpt_dir", default="checkpoints")
    p.add_argument("--trunk_name", default="rigid_trunk")
    p.add_argument("--ncup_name", default="rigid_ncup")
    p.add_argument("--val_length", type=int, default=64,
                   help="held-out pairs per evaluation")
    p.add_argument("--eval_seeds", default="999,1000,1001",
                   help="comma-joined held-out split seeds; both twins "
                   "are evaluated once per seed and the boundary-band "
                   "delta gets a bootstrap CI over the per-seed values")
    p.add_argument("--out", default="docs/ncup_vs_bilinear.json")
    a = p.parse_args()
    eval_seeds = [int(s) for s in a.eval_seeds.split(",") if s.strip()]
    if not eval_seeds:
        p.error("--eval_seeds must name at least one seed")

    # train.py subprocesses run with cwd=REPO, so relative paths must be
    # anchored there too or skip-checks look in the caller's cwd.
    a.ckpt_dir = os.path.join(REPO, a.ckpt_dir)
    trunk_dir = os.path.join(a.ckpt_dir, a.trunk_name)
    ncup_dir = os.path.join(a.ckpt_dir, a.ncup_name)
    if not os.path.isdir(os.path.join(trunk_dir, str(a.trunk_steps))):
        sh([sys.executable, "train.py"] + train_argv(a, "trunk"))
    if not os.path.isdir(os.path.join(ncup_dir, str(a.ncup_steps))):
        sh([sys.executable, "train.py"] + train_argv(a, "ncup"))

    # ---- evaluation: both twins on the identical held-out rigid split.
    from raft_ncup_tpu.utils.runtime import force_platform

    force_platform("cpu")
    import jax

    from raft_ncup_tpu.cli import parse_train
    from raft_ncup_tpu.evaluation import validate_synthetic_rigid
    from raft_ncup_tpu.models import get_model
    from raft_ncup_tpu.training.checkpoint import (
        load_pretrained_trunk,
        restore_variables,
    )

    eval_kw = dict(iters=12, batch_size=4, size_hw=(96, 128),
                   length=a.val_length)
    # results[twin][seed] -> metric dict; twin variables load ONCE.
    results: dict[str, dict[int, dict]] = {}

    def twin_variables(twin: str):
        _, model_cfg, _, _ = parse_train(train_argv(a, twin))
        model = get_model(model_cfg)
        if twin == "ncup":
            variables = restore_variables(ncup_dir)
        else:
            # Parameter-free head: the frozen trunk IS the whole model.
            variables = model.init(jax.random.PRNGKey(0), (1, 64, 96, 3))
            variables = load_pretrained_trunk(trunk_dir, variables)
        return model, variables

    for twin in ("bilinear", "ncup"):
        model, variables = twin_variables(twin)
        results[twin] = {}
        for es in eval_seeds:
            print(f"== evaluating twin: {twin} (split seed {es})",
                  flush=True)
            results[twin][es] = validate_synthetic_rigid(
                model, variables, seed=es, **eval_kw
            )

    # Per-seed deltas (bilinear - ncup; positive = NCUP wins) and the
    # bootstrap CI over the seed dimension for each metric.
    per_seed_delta = {
        k.replace("synthetic_rigid", "delta"): [
            results["bilinear"][es][k] - results["ncup"][es][k]
            for es in eval_seeds
        ]
        for k in results["ncup"][eval_seeds[0]]
    }
    ci = {k: bootstrap_ci(v, seed=a.seed)
          for k, v in per_seed_delta.items()}
    # Seed-pooled means keep the pre-CI record fields comparable.
    mean = {
        twin: {
            k: float(np.mean([results[twin][es][k] for es in eval_seeds]))
            for k in results[twin][eval_seeds[0]]
        }
        for twin in results
    }
    record = {
        "experiment": "ncup_vs_bilinear",
        "trunk": {"dir": trunk_dir, "steps": a.trunk_steps},
        "ncup_steps": a.ncup_steps,
        "seed": a.seed,
        "eval": {
            "split": f"synthetic_rigid(seeds={eval_seeds})",
            "seeds": eval_seeds,
            **eval_kw,
        },
        "results": mean,
        "results_per_seed": {
            t: {str(es): r for es, r in results[t].items()} for t in results
        },
        "bilinear_minus_ncup": {k: v["mean"] for k, v in ci.items()},
        "bilinear_minus_ncup_per_seed": per_seed_delta,
        "bootstrap_ci": ci,
    }
    os.makedirs(os.path.dirname(os.path.join(REPO, a.out)), exist_ok=True)
    with open(os.path.join(REPO, a.out), "w") as f:
        json.dump(record, f, indent=2)
    print(json.dumps(record["bilinear_minus_ncup"]))

    rows = [
        ("bilinear (frozen trunk)", mean["bilinear"]),
        ("NCUP (trained on frozen trunk)", mean["ncup"]),
    ]
    print(f"\n(means over {len(eval_seeds)} held-out split seeds)")
    print("| upsampler | EPE | boundary EPE | interior EPE |")
    print("|---|---|---|---|")
    for name, r in rows:
        print(
            f"| {name} | {r['synthetic_rigid']:.3f} "
            f"| {r['synthetic_rigid_bnd']:.3f} "
            f"| {r['synthetic_rigid_interior']:.3f} |"
        )
    bnd = ci["delta_bnd"]
    print(
        f"\nboundary-band delta (bilinear - ncup): {bnd['mean']:.4f} "
        f"[{bnd['ci_lo']:.4f}, {bnd['ci_hi']:.4f}] "
        f"({100 * (1 - bnd['alpha']):.0f}% bootstrap CI over "
        f"{bnd['n_values']} seeds; claim established only if the "
        "interval excludes 0)"
    )
    print(f"record written to {a.out}")


if __name__ == "__main__":
    main()
