#!/bin/bash
# TPU re-make of the reference KITTI fine-tune (reference:
# train_raft_nc_kitti.sh:13-28): 50k steps, crop 288x960, lr 1e-4,
# gamma 0.85, wdecay 1e-5.
set -e
EXP=raft_nc_kitti_ft

python -u train.py \
  --name "$EXP" \
  --model raft_nc_dbl \
  --load_pretrained models/raft-sintel.pth \
  --stage kitti \
  --num_steps 50000 \
  --batch_size 6 \
  --lr 0.0001 \
  --image_size 288 960 \
  --gamma 0.85 \
  --wdecay 0.00001 \
  --optimizer adamw \
  --scheduler cyclic \
  --final_upsampling=NConvUpsampler \
  --final_upsampling_scale=4 \
  --final_upsampling_use_data_for_guidance=True \
  --final_upsampling_channels_to_batch=True \
  --interp_net=NConvUNet \
  --interp_net_channels_multiplier=2 \
  --interp_net_num_downsampling=1 \
  --interp_net_data_pooling="conf_based" \
  --interp_net_encoder_filter_sz=5 \
  --interp_net_decoder_filter_sz=3 \
  --interp_net_out_filter_sz=1 \
  --interp_net_shared_encoder=True \
  --interp_net_use_bias=False \
  --weights_est_net=Simple \
  --weights_est_net_num_ch="[64, 32]" \
  --weights_est_net_filter_sz="[3, 3, 1]" \
  --weights_est_net_dilation="[1, 1, 1]" \
  "$@"
