#!/usr/bin/env python
"""Reassemble a request/stream journey from a flight-recorder dump.

Reads one ``flight_<trigger>_<ts>.json`` (observability/flight.py) and
prints a human-readable postmortem: the fault header (trigger, context,
mesh/precision fingerprints, health states, paging SLOs), then the
correlated timeline — every span and event in the dump's ring that
carries the chosen correlation id, in ring (arrival) order, using the
SAME matching semantics as the live ``SpanTracer.for_attr`` (a singular
``request_id`` matches a batch span's plural ``request_ids`` list, so
batch-level stages appear in a single request's journey).

The correlation id comes from ``--request_id`` / ``--stream_id`` /
``--batch_id``, or — the common case — from the dump's own trigger
context (a ``poison_quarantine`` dump names the quarantined request, a
``stream_anomaly_reset`` dump the reset stream).

``--telemetry_jsonl`` additionally replays the run's periodic snapshot
file (serve.py ``--telemetry_jsonl``) as a condensed health/SLO/queue
timeline around the fault — the slow-timescale context (was the queue
already deep? had the SLO been burning for three windows?) that the
bounded span ring cannot hold.

Host-only stdlib by construction, like everything it reads: a
postmortem must be runnable on a laptop from two files, with no jax and
no backend.

Fleet trees (docs/FLEET.md): a fleet run leaves one flight directory
per replica (``replica_<i>_flight/``) plus the router's own dumps under
one base dir. Point the tool at the DIRECTORY and it selects a dump
deterministically — ``--replica N`` restricts to that replica's
subtree, and "latest" is decided by the dump filename's embedded
(timestamp, sequence) pair, not filesystem mtime, so the same tree
always selects the same dump. The router attaches its correlation id at
dispatch as the replica-side request id, so one ``--request_id``
reassembles the journey across the router hop.

``--tree`` (directory input) additionally renders the STITCHED fleet
trace tree: every dump in the tree is merged by ``trace_id``
(observability/aggregate.py), replica timestamps are translated onto
the router's clock through the handshake offsets banked in the
``router_drain`` dump, and each trace prints as one cross-process
timeline — router root span, wire hop, replica admission/dispatch/drain
— with the per-hop latency breakdown. Torn dumps and truncated JSONL
lines (a replica killed mid-write) are skipped and counted, never
raised.

Usage:
    python scripts/postmortem.py flight_poison_quarantine_*.json
    python scripts/postmortem.py dump.json --request_id 12
    python scripts/postmortem.py dump.json --stream_id s3 \
        --telemetry_jsonl serve_telemetry.jsonl
    python scripts/postmortem.py fleet_run_dir/ --replica 1 --request_id 7
    python scripts/postmortem.py fleet_run_dir/ --tree --request_id 7
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from raft_ncup_tpu.observability.aggregate import (  # noqa: E402
    dump_sort_key as _dump_sort_key,
)
from raft_ncup_tpu.observability.flight import (  # noqa: E402
    load_dump,
    match_records,
)

# Context keys that can seed the correlation when no flag is given, in
# preference order (a request id is the most specific journey).
_CONTEXT_KEYS = ("request_id", "stream_id", "batch_id")

# Deterministic recency order for flight_<trigger>_<ts>_<seq> names:
# the ONE shared implementation (aggregate.dump_sort_key) — the
# aggregator's latest-dump choice and this tool's selection must never
# disagree about which dump is "latest".


def select_dump(tree: str, replica=None) -> str:
    """Pick ONE dump from a fleet flight tree: restrict to
    ``replica_<i>_flight/`` when ``--replica`` is given, then take the
    latest by the filename's (timestamp, seq) — falling back to the
    next-latest when the newest file is torn (a replica killed mid-run
    can leave a truncated dump; the postmortem of that very fault must
    not raise on its evidence). Raises with the candidate roster when
    nothing matches — an empty postmortem must say why."""
    from raft_ncup_tpu.observability.aggregate import load_dump_tolerant

    roots = []
    if replica is not None:
        sub = os.path.join(tree, f"replica_{replica}_flight")
        if not os.path.isdir(sub):
            raise FileNotFoundError(
                f"{tree}: no replica_{replica}_flight/ subtree "
                f"(have: {sorted(os.listdir(tree))})"
            )
        roots.append(sub)
    else:
        roots.append(tree)
    candidates = []
    for root in roots:
        for dirpath, _, files in os.walk(root):
            candidates.extend(
                os.path.join(dirpath, f)
                for f in files
                if f.startswith("flight_") and f.endswith(".json")
            )
    if not candidates:
        raise FileNotFoundError(
            f"no flight_*.json dumps under {roots}"
        )
    for path in sorted(candidates, key=_dump_sort_key, reverse=True):
        if load_dump_tolerant(path) is not None:
            return path
        print(
            f"skipping torn/unreadable dump {os.path.basename(path)}",
            file=sys.stderr,
        )
    raise FileNotFoundError(
        f"every flight_*.json under {roots} is torn/unreadable"
    )


def _pick_correlation(args, context: dict) -> dict:
    explicit = {
        k: v
        for k, v in (
            ("request_id", args.request_id),
            ("stream_id", args.stream_id),
            ("batch_id", args.batch_id),
        )
        if v is not None
    }
    if explicit:
        return explicit
    for key in _CONTEXT_KEYS:
        if key in context:
            return {key: context[key]}
    return {}


def _fmt_attrs(attrs: dict, skip=()) -> str:
    parts = []
    for k in sorted(attrs):
        if k in skip:
            continue
        v = attrs[k]
        if isinstance(v, list) and len(v) > 6:
            v = f"[{len(v)} items]"
        parts.append(f"{k}={v}")
    return " ".join(parts)


def _print_journey(records, match: dict) -> int:
    matched = match_records(records, **match) if match else records
    label = (
        " ".join(f"{k}={v}" for k, v in match.items())
        if match else "full ring (no correlation id)"
    )
    print(f"journey [{label}]: {len(matched)} record(s)")
    for r in matched:
        kind = "event" if r.get("event") else "span "
        dur = r.get("duration_ms")
        dur_s = f"{dur:9.3f} ms" if dur is not None else "         --"
        print(f"  {kind} {dur_s}  {r['name']:<28} "
              f"{_fmt_attrs(r.get('attrs', {}))}")
    return len(matched)


def _print_snapshot_timeline(path: str, subsystems) -> None:
    print(f"\nsnapshot timeline ({path}):")
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("name") != "telemetry_snapshot":
                continue
            rep = rec.get("report", {})
            gauges = rep.get("metrics", {}).get("gauges", {})
            health = rep.get("health", {}) or {}
            slo = rep.get("slo") or {}
            states = " ".join(
                f"{name}={snap.get('state')}"
                for name, snap in sorted(health.items())
                if not subsystems or name in subsystems
            )
            depths = " ".join(
                f"{k}={v.get('value'):g}"
                for k, v in sorted(gauges.items())
                if k.endswith("_queue_depth")
            )
            paging = ",".join(slo.get("paging", [])) or "-"
            print(
                f"  t={rec.get('time_unix_s')}  {states or 'health=-'}  "
                f"{depths}  paging={paging}"
            )


def _print_fleet_tree(tree: str, args) -> int:
    """The stitched fleet trace tree (--tree): merge the router's and
    every replica's latest dumps (observability/aggregate.py), translate
    replica timestamps through the handshake's clock offsets, and print
    one cross-process timeline per trace — root router span down to the
    replica device spans — with the per-hop breakdown. Next to the
    flight-tree view, not instead of it: the dump view is one process's
    ring, this is the fleet's."""
    from raft_ncup_tpu.observability.aggregate import (
        collect_fleet_records,
        fleet_traces,
        render_trace,
    )

    collected = collect_fleet_records(tree)
    traces = fleet_traces(
        collected,
        request_id=args.request_id,
    )
    print(
        f"\nfleet trace tree ({tree}): {len(traces)} trace(s), "
        f"origins={sorted(collected['origins'])}, "
        f"gaps={collected['gaps']}, "
        f"skipped_dumps={collected['skipped_dumps']}"
    )
    for trace in traces:
        for line in render_trace(trace):
            print(line)
    if not traces:
        print(
            "no cross-process traces found — the run predates trace "
            "propagation, or the rings aged the journey out before "
            "the dumps", file=sys.stderr,
        )
        return 1
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Reassemble a request/stream journey from a "
        "flight-recorder dump"
    )
    parser.add_argument("dump", help="flight_<trigger>_<ts>.json path, "
                        "or a fleet flight directory (latest dump "
                        "selected deterministically; --replica narrows)")
    parser.add_argument("--request_id", type=int, default=None)
    parser.add_argument("--stream_id", default=None)
    parser.add_argument("--batch_id", type=int, default=None)
    parser.add_argument("--replica", type=int, default=None,
                        help="[directory input] select the dump from "
                        "this replica's replica_<i>_flight/ subtree")
    parser.add_argument("--tree", action="store_true",
                        help="[directory input] additionally render the "
                        "stitched FLEET trace tree: router root spans "
                        "down to replica device spans, per-hop "
                        "breakdown (observability/aggregate.py)")
    parser.add_argument("--telemetry_jsonl", default=None,
                        help="serve.py --telemetry_jsonl file: print the "
                        "condensed health/SLO/queue timeline around the "
                        "fault")
    args = parser.parse_args(argv)

    dump_path = args.dump
    if os.path.isdir(dump_path):
        dump_path = select_dump(dump_path, replica=args.replica)
        print(f"selected dump: {os.path.relpath(dump_path, args.dump)}")
    elif args.replica is not None or args.tree:
        print("--replica/--tree only apply to a directory input",
              file=sys.stderr)
        return 2
    dump = load_dump(dump_path)
    context = dump.get("context", {})
    print(f"flight dump: {os.path.basename(dump_path)}")
    print(f"  trigger:      {dump['trigger']}")
    print(f"  time_unix_s:  {dump.get('time_unix_s')}")
    if context:
        print(f"  context:      {_fmt_attrs(context)}")
    fps = dump.get("fingerprints") or {}
    if fps:
        print(f"  fingerprints: {_fmt_attrs(fps)}")
    report = dump.get("report") or {}
    health = report.get("health") or {}
    for name, snap in sorted(health.items()):
        print(
            f"  health:       {name}={snap.get('state')} "
            f"({snap.get('reason', '')})"
        )
    slo = report.get("slo") or {}
    for name, v in sorted((slo.get("verdicts") or {}).items()):
        if v.get("page"):
            print(
                f"  slo PAGING:   {name} burn_fast={v.get('burn_fast')} "
                f"burn_slow={v.get('burn_slow')}"
            )
    print()
    match = _pick_correlation(args, context)
    n = _print_journey(dump.get("spans", []), match)
    tree_rc = _print_fleet_tree(args.dump, args) if args.tree else 0
    if args.telemetry_jsonl:
        _print_snapshot_timeline(
            args.telemetry_jsonl, set(health) or None
        )
    if tree_rc:
        return tree_rc
    if n == 0:
        print("no records matched — wrong id, or the journey aged out "
              "of the bounded ring before the dump", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
