#!/usr/bin/env python
"""The on-call "why is p99 up" tool: print the slowest N traces from a
fleet export directory with their per-hop latency breakdown.

Reads the same export tree a fleet run leaves under its topology
``base_dir`` — the router's flight dumps (``fleet_request`` root spans,
clock-handshake offsets) and each replica's ``replica_<i>_flight/``
dumps — stitches them by ``trace_id`` (observability/aggregate.py), and
ranks by end-to-end latency. The hop columns answer the attribution
question directly: a p99 regression that lives in ``replica_queue`` is
an admission/batching problem, one in ``device`` is a compute problem,
one in ``wire``/``return`` is the transport — three different pages.

Host-only stdlib, like everything it reads (the aggregate module is
inside JGL010's scope): runnable on a laptop from the export directory,
no jax, no backend.

Usage:
    python scripts/trace_report.py fleet_run_dir/
    python scripts/trace_report.py fleet_run_dir/ --top 5
    python scripts/trace_report.py fleet_run_dir/ --request_id 7 --tree
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from raft_ncup_tpu.observability.aggregate import (  # noqa: E402
    collect_fleet_records,
    fleet_traces,
    render_trace,
)

_HOP_COLUMNS = (
    ("router_queue_ms", "router_q"),
    ("wire_ms", "wire"),
    ("replica_queue_ms", "replica_q"),
    ("device_ms", "device"),
    ("return_ms", "return"),
)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Slowest-N fleet traces with per-hop breakdown"
    )
    parser.add_argument("export_dir", help="fleet run base_dir (router "
                        "+ replica flight dumps)")
    parser.add_argument("--top", type=int, default=10,
                        help="how many traces to print (slowest first)")
    parser.add_argument("--request_id", type=int, default=None,
                        help="narrow to one request's trace")
    parser.add_argument("--tree", action="store_true",
                        help="also print each trace's full stitched "
                        "timeline, not just the hop columns")
    args = parser.parse_args(argv)

    if not os.path.isdir(args.export_dir):
        print(f"{args.export_dir}: not a directory", file=sys.stderr)
        return 2
    collected = collect_fleet_records(args.export_dir)
    traces = fleet_traces(collected, request_id=args.request_id)
    print(
        f"{args.export_dir}: {len(traces)} trace(s) across "
        f"{sorted(collected['origins'])}"
        + (f", gaps={collected['gaps']}" if collected["gaps"] else "")
        + (
            f", skipped_dumps={collected['skipped_dumps']}"
            if collected["skipped_dumps"] else ""
        )
    )
    if not traces:
        print(
            "no traces found — not a fleet export dir, or the run "
            "predates trace propagation", file=sys.stderr,
        )
        return 1

    header = (
        f"{'trace':<18} {'rid':>5} {'total':>9}  "
        + "  ".join(f"{label:>9}" for _, label in _HOP_COLUMNS)
    )
    print(header)
    print("-" * len(header))
    for trace in traces[: max(1, args.top)]:
        hops = trace.get("hops") or {}
        total = trace.get("total_ms")
        cols = "  ".join(
            f"{hops[k]:>7.1f}ms" if k in hops else f"{'--':>9}"
            for k, _ in _HOP_COLUMNS
        )
        print(
            f"{trace['trace_id']:<18} "
            f"{str(trace.get('request_id')):>5} "
            + (f"{total:>7.1f}ms" if total is not None else f"{'--':>9}")
            + f"  {cols}"
        )
        if args.tree:
            for line in render_trace(trace):
                print("  " + line)
    return 0


if __name__ == "__main__":
    sys.exit(main())
