#!/usr/bin/env python
"""Execute ONE real 1088x1920 / 32-iteration test-mode forward and report
peak RSS + wall time — the out-of-band evidence behind docs/PERF.md's
"1080p executed for real" row.

tests/test_highres.py pins the 1080p memory story with *compiler memory
analysis* (platform-independent, cheap); this script is the complement:
it actually executes the flagship onthefly-corr configuration at full
1080p shape and measures what the OS saw. CPU is an honest stand-in for
"does the working set fit": ru_maxrss upper-bounds the XLA temp +
argument + output footprint the analysis predicts (host arenas and the
compiler itself add overhead on top, which is why both numbers are
recorded side by side).

Usage:
    JAX_PLATFORMS=cpu python scripts/highres_forward.py [--iters 32]
        [--size 1088 1920] [--corr_impl onthefly]

Prints one JSON line: shape, iters, compile_s, run_s (the executed
forward, compile excluded), peak_rss_gib, memory-analysis bytes for the
same executable.
"""

from __future__ import annotations

import argparse
import json
import os
import resource
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--size", type=int, nargs=2, default=[1088, 1920],
                   metavar=("H", "W"))
    p.add_argument("--iters", type=int, default=32)
    p.add_argument("--corr_impl", default="onthefly",
                   choices=["onthefly", "volume", "pallas"])
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from raft_ncup_tpu.config import flagship_config
    from raft_ncup_tpu.models import get_model

    h, w = args.size
    cfg = flagship_config(dataset="sintel", corr_impl=args.corr_impl)
    model = get_model(cfg)
    variables = model.init(jax.random.PRNGKey(0), (1, 64, 64, 3))

    def fwd(v, i1, i2):
        return model.apply(v, i1, i2, iters=args.iters, test_mode=True)

    img = jax.ShapeDtypeStruct((1, h, w, 3), jnp.float32)
    t0 = time.perf_counter()
    compiled = jax.jit(fwd).lower(variables, img, img).compile()
    compile_s = time.perf_counter() - t0
    mem = compiled.memory_analysis()

    rng = np.random.default_rng(0)
    img1 = jnp.asarray(rng.uniform(0, 255, (1, h, w, 3)), jnp.float32)
    img2 = jnp.asarray(rng.uniform(0, 255, (1, h, w, 3)), jnp.float32)
    t0 = time.perf_counter()
    lr, up = compiled(variables, img1, img2)
    jax.block_until_ready((lr, up))
    run_s = time.perf_counter() - t0

    finite = bool(jnp.isfinite(up).all())
    # Linux ru_maxrss is KiB.
    peak_rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    report = {
        "shape": [1, h, w, 3],
        "iters": args.iters,
        "corr_impl": args.corr_impl,
        "platform": jax.default_backend(),
        "compile_s": round(compile_s, 1),
        "run_s": round(run_s, 1),
        "finite": finite,
        "peak_rss_gib": round(peak_rss / 2**30, 2),
        "analysis_temp_gib": round(
            int(mem.temp_size_in_bytes) / 2**30, 2
        ),
        "analysis_total_gib": round(
            (
                int(mem.temp_size_in_bytes)
                + int(mem.argument_size_in_bytes)
                + int(mem.output_size_in_bytes)
            )
            / 2**30,
            2,
        ),
    }
    print(json.dumps(report), flush=True)
    return 0 if finite else 1


if __name__ == "__main__":
    sys.exit(main())
