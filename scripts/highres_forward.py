#!/usr/bin/env python
"""Execute ONE real 1088x1920 / 32-iteration test-mode forward —
optionally spatially sharded — and report peak RSS + wall time: the
out-of-band evidence behind docs/PERF.md's "1080p executed for real"
and "spatially sharded 1080p executed" rows.

tests/test_highres.py pins the 1080p memory story with *compiler memory
analysis* (platform-independent, cheap); this script is the complement:
it actually executes the flagship onthefly-corr configuration at full
1080p shape and measures what the OS saw. CPU is an honest stand-in for
"does the working set fit": ru_maxrss upper-bounds the XLA temp +
argument + output footprint the analysis predicts (host arenas and the
compiler itself add overhead on top, which is why both numbers are
recorded side by side).

``--spatial N`` (N > 1) runs the SAME forward as one SPMD program on a
(1 data x N spatial) mesh. On a host with fewer than N real devices the
CPU platform is split into N virtual devices
(``--xla_force_host_platform_device_count``, the tests/conftest.py
mechanism), so the report's ``analysis_*`` numbers become PER-DEVICE:
they should drop roughly with the shard count, matching
tests/test_highres.py's compile-time claim — now on an executed
program. Note the CPU-emulation caveat (docs/SHARDING.md): all N
virtual devices share one address space, so ``peak_rss_gib`` still
aggregates every shard; per-device footprint is the ``analysis_*``
fields. ``collectives``/``collective_bytes`` fingerprint the sharding
(0/0 when unsharded).

``--size 2176 3840`` is the UHD/4K configuration the banded Pallas
corr tier (ops/corr_pallas.py; docs/PERF.md "Banded dispatch") exists
for: with ``--corr_impl pallas`` the report's ``corr_dispatch`` field
shows which tier (resident kernel / banded kernel / XLA fallback)
carried each pyramid level, and the executed forward is the evidence
that 4K fits and runs. ``--precision bf16_infer`` runs the same
forward under the bf16 policy — halving the 4K working set — which
was previously unmeasurable out-of-band.

Usage:
    JAX_PLATFORMS=cpu python scripts/highres_forward.py [--iters 32]
        [--size 1088 1920] [--corr_impl onthefly] [--spatial 2]
        [--precision f32]

Prints one JSON line: shape, iters, mesh, precision, compile_s, run_s
(the executed forward, compile excluded), peak_rss_gib, per-device
memory-analysis bytes and collective stats for the same executable,
plus corr_dispatch/corr_tuning when the Pallas tiers are in play.
"""

from __future__ import annotations

import argparse
import json
import os
import resource
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--size", type=int, nargs=2, default=[1088, 1920],
                   metavar=("H", "W"))
    p.add_argument("--iters", type=int, default=32)
    p.add_argument("--corr_impl", default="onthefly",
                   choices=["onthefly", "volume", "pallas"])
    p.add_argument("--precision", default="f32",
                   choices=["f32", "bf16_infer"],
                   help="precision-policy preset the forward compiles "
                   "under (docs/PRECISION.md); bf16_infer halves the "
                   "corr working set and doubles the Pallas VMEM "
                   "dispatch thresholds")
    p.add_argument("--spatial", type=int, default=1,
                   help="shard the image height over this many devices "
                   "(1 = unsharded). On CPU, forces this many virtual "
                   "host devices BEFORE jax initializes.")
    args = p.parse_args(argv)

    if args.spatial > 1:
        # Must land before the first jax import: device count is fixed
        # at backend init. Harmless when real devices already exist.
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags
                + f" --xla_force_host_platform_device_count={args.spatial}"
            ).strip()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from raft_ncup_tpu.config import flagship_config
    from raft_ncup_tpu.models import get_model
    from raft_ncup_tpu.parallel.mesh import (
        collective_stats,
        make_mesh,
        mesh_fingerprint,
    )
    from raft_ncup_tpu.parallel.step import make_eval_step

    h, w = args.size
    if (h // 8) % args.spatial:
        raise SystemExit(
            f"--spatial {args.spatial} must divide height/8 = {h // 8} "
            "(pad with InputPadder(divisor=8*spatial) first)"
        )
    cfg = flagship_config(
        dataset="sintel", corr_impl=args.corr_impl,
        precision=args.precision,
    )
    model = get_model(cfg)
    variables = model.init(jax.random.PRNGKey(0), (1, 64, 64, 3))

    corr_dispatch = None
    if args.corr_impl == "pallas":
        # Trace-time tier tally (resident kernel / banded / XLA
        # fallback per pyramid level) — read after the single compile
        # below, the one-reset-one-lowering discipline the counts
        # document.
        from raft_ncup_tpu.ops import corr_pallas as cpk

        cpk.reset_dispatch_counts()

    mesh = (
        make_mesh(data=1, spatial=args.spatial,
                  devices=jax.devices()[: args.spatial])
        if args.spatial > 1
        else None
    )
    step = make_eval_step(model, iters=args.iters, mesh=mesh)

    img = jax.ShapeDtypeStruct((1, h, w, 3), jnp.float32)
    t0 = time.perf_counter()
    compiled = step.lower(variables, img, img).compile()
    compile_s = time.perf_counter() - t0
    if args.corr_impl == "pallas":
        corr_dispatch = cpk.dispatch_counts()
    mem = compiled.memory_analysis()
    try:
        coll = collective_stats(compiled.as_text())
    except Exception as e:  # pragma: no cover - backend-specific text
        print(f"collective_stats unavailable: {e}", file=sys.stderr)
        coll = {"collectives": None, "collective_bytes": None}

    rng = np.random.default_rng(0)
    img1 = jnp.asarray(rng.uniform(0, 255, (1, h, w, 3)), jnp.float32)
    img2 = jnp.asarray(rng.uniform(0, 255, (1, h, w, 3)), jnp.float32)
    t0 = time.perf_counter()
    lr, up = compiled(variables, img1, img2)
    jax.block_until_ready((lr, up))
    run_s = time.perf_counter() - t0

    finite = bool(jnp.isfinite(up).all())
    # Linux ru_maxrss is KiB.
    peak_rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    from raft_ncup_tpu.ops.corr import corr_tuning_meta

    report = {
        "shape": [1, h, w, 3],
        "iters": args.iters,
        "corr_impl": args.corr_impl,
        "precision": args.precision,
        "platform": jax.default_backend(),
        "mesh": mesh_fingerprint(mesh),
        "devices": args.spatial,
        "compile_s": round(compile_s, 1),
        "run_s": round(run_s, 1),
        "finite": finite,
        "peak_rss_gib": round(peak_rss / 2**30, 2),
        # memory_analysis of an SPMD executable is PER DEVICE: under
        # --spatial N these should drop roughly with N.
        "analysis_temp_gib": round(
            int(mem.temp_size_in_bytes) / 2**30, 2
        ),
        "analysis_total_gib": round(
            (
                int(mem.temp_size_in_bytes)
                + int(mem.argument_size_in_bytes)
                + int(mem.output_size_in_bytes)
            )
            / 2**30,
            2,
        ),
        **coll,
        "corr_tuning": corr_tuning_meta(),
    }
    if corr_dispatch is not None:
        # Which tier carried each pyramid level (three-tier dispatch,
        # ops/corr_pallas.py): the 4K acceptance evidence is
        # fallback == 0 — every level on a kernel tier.
        report["corr_dispatch"] = corr_dispatch
    print(json.dumps(report), flush=True)
    return 0 if finite else 1


if __name__ == "__main__":
    sys.exit(main())
