#!/bin/bash
# graftlint over everything that ships: the package, the drivers, the
# bench and the scripts. Strict allowlist mode — an entry that no longer
# suppresses anything must be deleted (or its finding has come back).
# Rule catalog + allowlist format: docs/ANALYSIS.md.
set -e
cd "$(dirname "$0")/.."
exec python -m raft_ncup_tpu.analysis \
    --strict-allowlist \
    raft_ncup_tpu/ train.py evaluate.py demo.py serve.py bench.py scripts/ \
    "$@"
