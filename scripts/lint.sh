#!/bin/bash
# graftlint over everything that ships: the package, the drivers, the
# bench and the scripts. Strict allowlist mode — an entry that no longer
# suppresses anything must be deleted (or its finding has come back).
# Rule catalog + allowlist format: docs/ANALYSIS.md.
# raft_ncup_tpu/observability/ is named explicitly (it is also inside
# the package glob): JGL010 holds the telemetry subsystem host-only, and
# the redundant path keeps that scope visible even if the package line
# is ever narrowed.
set -e
cd "$(dirname "$0")/.."
exec python -m raft_ncup_tpu.analysis \
    --strict-allowlist \
    raft_ncup_tpu/ raft_ncup_tpu/observability/ \
    train.py evaluate.py demo.py serve.py bench.py scripts/ \
    "$@"
