#!/bin/bash
# graftlint over everything that ships: the package, the drivers, the
# bench and the scripts. Strict allowlist mode — an entry that no longer
# suppresses anything must be deleted (or its finding has come back).
# Rule catalog + allowlist format: docs/ANALYSIS.md.
# raft_ncup_tpu/observability/ and raft_ncup_tpu/fleet/ are named
# explicitly (they are also inside the package glob): JGL010 holds the
# telemetry subsystem AND the fleet control plane host-only, and the
# redundant paths keep that scope visible even if the package line is
# ever narrowed.
set -e
cd "$(dirname "$0")/.."
exec python -m raft_ncup_tpu.analysis \
    --strict-allowlist \
    raft_ncup_tpu/ raft_ncup_tpu/observability/ raft_ncup_tpu/fleet/ \
    train.py evaluate.py demo.py serve.py bench.py scripts/ \
    "$@"
