#!/bin/bash
# One-command TPU evidence ritual (VERDICT r4 #1).
#
# The axon tunnel has been wedged for four rounds; when it un-wedges the
# window may be short. This script banks ALL the hardware evidence in one
# invocation, and every attempt — successful or not — is logged to
# docs/tpu_probe_log.md so the wedge history stays auditable:
#
#   1. bounded backend probe (never touches jax.devices() in-process);
#   2. if a live accelerator answers:
#        pytest tests_tpu/            (Mosaic compile + timing of both kernels)
#        python bench.py              (full-shape row + variant rows, baselines
#                                      auto-pinned in docs/perf_baseline.json)
#        scripts/flip_recommendations.py   (data-driven default flips for
#                                      corr_impl / RAFT_NCUP_NCONV_IMPL)
#   3. else: the logged probe row is the evidence of the attempt.
#
# Env: RITUAL_PROBE_TIMEOUT (s, default 120) bounds the probe.
# pipefail: the pytest status must survive the tee|tail pipelines below,
# or a failing tests_tpu run would log "green" in the audit row.
set -u -o pipefail
cd "$(dirname "$0")/.."

LOGFILE=docs/tpu_probe_log.md
if [ ! -f "$LOGFILE" ]; then
    cat > "$LOGFILE" <<'EOF'
# TPU probe log

Every `scripts/tpu_ritual.sh` attempt to reach the axon TPU tunnel, in
order. The bounded probe runs `jax.devices()` in a watchdogged child
(`raft_ncup_tpu/utils/backend_probe.py`) because the wedged tunnel HANGS
rather than failing fast (docs/PERF.md round-4 postmortem).

| when (UTC) | duration | platform | outcome | follow-up |
|---|---|---|---|---|
EOF
fi

TS=$(date -u +"%Y-%m-%dT%H:%M:%SZ")
PROBE_OUT=$(python - <<'EOF'
import os, time
from raft_ncup_tpu.utils.backend_probe import probe_backend
t0 = time.time()
r = probe_backend(timeout_s=float(os.environ.get("RITUAL_PROBE_TIMEOUT", "120")))
print(f"{time.time()-t0:.0f}s|{r.platform or '-'}|{r.reason}")
EOF
)
DUR=$(echo "$PROBE_OUT" | cut -d'|' -f1)
PLATFORM=$(echo "$PROBE_OUT" | cut -d'|' -f2)
REASON=$(echo "$PROBE_OUT" | cut -d'|' -f3)
echo "probe: platform=$PLATFORM reason=$REASON after $DUR"

if [ "$REASON" = "ok" ] && [ "$PLATFORM" != "cpu" ] && [ "$PLATFORM" != "-" ]; then
    FOLLOWUP=""
    echo "== live accelerator ($PLATFORM): running tests_tpu/"
    if python -m pytest tests_tpu/ -q -rs 2>&1 | tee /tmp/ritual_tests.log | tail -3; then
        FOLLOWUP="tests_tpu green; "
    else
        FOLLOWUP="tests_tpu FAILED (see /tmp/ritual_tests.log); "
    fi
    echo "== running bench.py (full shape + variant rows)"
    python bench.py 2> >(tail -5 >&2) | tee /tmp/ritual_bench.out | tail -1
    if tail -1 /tmp/ritual_bench.out | python scripts/flip_recommendations.py; then
        FOLLOWUP="${FOLLOWUP}bench row recorded (see docs/perf_baseline.json)"
    fi
    echo "| $TS | $DUR | $PLATFORM | live | $FOLLOWUP |" >> "$LOGFILE"
    echo "== evidence banked. Append the bench row + recommendations to docs/PERF.md."
else
    echo "| $TS | $DUR | $PLATFORM | $REASON | none (no accelerator) |" >> "$LOGFILE"
    echo "== tunnel not available ($REASON); attempt logged in $LOGFILE"
fi
