#!/bin/bash
# One-command TPU evidence ritual (VERDICT r4 #1).
#
# The axon tunnel has been wedged for four rounds; when it un-wedges the
# window may be short. This script banks ALL the hardware evidence in one
# invocation, and every attempt — successful or not — is logged to
# docs/tpu_probe_log.md so the wedge history stays auditable:
#
#   1. bounded backend probe (never touches jax.devices() in-process);
#   2. if a live accelerator answers:
#        pytest tests_tpu/            (Mosaic compile + timing of both kernels)
#        python bench.py              (full-shape row + variant rows, baselines
#                                      auto-pinned in docs/perf_baseline.json)
#        scripts/flip_recommendations.py   (data-driven default flips for
#                                      corr_impl / RAFT_NCUP_NCONV_IMPL)
#   3. else: the logged probe row is the evidence of the attempt.
#
# Env: RITUAL_PROBE_TIMEOUT (s, default 120) bounds the probe.
# pipefail: the pytest status must survive the tee|tail pipelines below,
# or a failing tests_tpu run would log "green" in the audit row.
set -u -o pipefail
cd "$(dirname "$0")/.."

LOGFILE=docs/tpu_probe_log.md
if [ ! -f "$LOGFILE" ]; then
    # Bootstrap only (the committed docs/tpu_probe_log.md is the
    # authoritative copy, header documentation included).
    printf '# TPU probe log\n\nSee scripts/tpu_ritual.sh.\n\n| when (UTC) | duration | platform | outcome | follow-up |\n|---|---|---|---|---|\n' > "$LOGFILE"
fi

TS=$(date -u +"%Y-%m-%dT%H:%M:%SZ")
# Take only the LAST line (stray jax/absl stdout noise must not corrupt
# the parsed fields), and fail loudly on an empty/failed probe script —
# a malformed audit row would defeat the log's purpose.
PROBE_OUT=$(python - <<'EOF' | tail -1
import os, time
from raft_ncup_tpu.utils.backend_probe import probe_backend
t0 = time.time()
r = probe_backend(timeout_s=float(os.environ.get("RITUAL_PROBE_TIMEOUT", "120")))
print(f"{time.time()-t0:.0f}s|{r.platform or '-'}|{r.reason}")
EOF
)
case "$PROBE_OUT" in
    *'|'*'|'*) : ;;  # well-formed dur|platform|reason
    *)
        echo "ritual: probe script failed (output: '$PROBE_OUT')" >&2
        echo "| $TS | - | - | probe-script-error | none |" >> "$LOGFILE"
        exit 1
        ;;
esac
DUR=$(echo "$PROBE_OUT" | cut -s -d'|' -f1)
PLATFORM=$(echo "$PROBE_OUT" | cut -s -d'|' -f2)
REASON=$(echo "$PROBE_OUT" | cut -s -d'|' -f3)
echo "probe: platform=$PLATFORM reason=$REASON after $DUR"

if [ "$REASON" = "ok" ] && [ "$PLATFORM" != "cpu" ] && [ "$PLATFORM" != "-" ]; then
    # Evidence must survive the session: /tmp dies with the host, so the
    # banked record/recommendations/test-tail go into the committed log.
    EVDIR=docs/tpu_evidence
    mkdir -p "$EVDIR"
    STAMP=$(echo "$TS" | tr ':' '-')
    FOLLOWUP=""
    echo "== live accelerator ($PLATFORM): running tests_tpu/"
    if python -m pytest tests_tpu/ -q -rs 2>&1 | tee "$EVDIR/tests_$STAMP.log" | tail -3; then
        FOLLOWUP="tests_tpu green; "
    else
        FOLLOWUP="tests_tpu FAILED; "
    fi
    echo "== running bench.py (full shape + variant rows)"
    python bench.py 2> >(tail -5 >&2) | tee "$EVDIR/bench_$STAMP.out" | tail -1
    if tail -1 "$EVDIR/bench_$STAMP.out" | python scripts/flip_recommendations.py \
        | tee "$EVDIR/flips_$STAMP.txt"; then
        FOLLOWUP="${FOLLOWUP}bench row recorded (docs/perf_baseline.json, $EVDIR/)"
    fi
    # The evidence block lives in its own committed file so the audit
    # TABLE stays contiguous (markdown tables end at the first non-table
    # line; an inline block would orphan every later row).
    {
        echo "# Evidence $TS"
        echo
        echo '```'
        tail -1 "$EVDIR/bench_$STAMP.out"
        cat "$EVDIR/flips_$STAMP.txt" 2>/dev/null
        echo '```'
    } > "$EVDIR/evidence_$STAMP.md"
    echo "| $TS | $DUR | $PLATFORM | live | $FOLLOWUP — $EVDIR/evidence_$STAMP.md |" >> "$LOGFILE"
    echo "== evidence banked in $EVDIR/ (row appended to $LOGFILE); commit these files."
else
    echo "| $TS | $DUR | $PLATFORM | $REASON | none (no accelerator) |" >> "$LOGFILE"
    echo "== tunnel not available ($REASON); attempt logged in $LOGFILE"
fi
