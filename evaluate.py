#!/usr/bin/env python
"""Evaluation driver (reference-compatible CLI).

Validates on chairs / sintel / kitti or writes leaderboard submissions
(reference: evaluate.py:185-272). Checkpoints: an orbax run dir produced
by our train.py, or a PyTorch ``.pth`` from the reference (imported
weight-by-weight).

Examples:
    python evaluate.py --model raft_nc_dbl --dataset sintel \
        --restore_ckpt checkpoints/raft_nc_sintel
    python evaluate.py --model raft_nc_dbl --dataset kitti --submission \
        --restore_ckpt models/raft_nc-kitti.pth
"""

from __future__ import annotations

import sys

import jax


def load_variables(model, model_cfg, restore_ckpt: str | None):
    """Init variables, then overwrite from the checkpoint (strict for
    torch files, as in the reference eval — evaluate.py:257)."""
    import os

    # Parameter shapes are input-size independent (fully convolutional);
    # init small to keep startup cheap.
    shape = (1, 64, 96, 3)
    variables = model.init(jax.random.PRNGKey(0), shape)
    if not restore_ckpt:
        return variables
    if os.path.isdir(restore_ckpt):
        from raft_ncup_tpu.training.checkpoint import restore_variables

        restored = restore_variables(restore_ckpt)
        variables["params"] = restored["params"]
        if "batch_stats" in restored:
            variables["batch_stats"] = restored["batch_stats"]
        return variables
    from raft_ncup_tpu.training.checkpoint import load_torch

    return load_torch(restore_ckpt, variables, strict=True)


def main(argv=None) -> None:
    from raft_ncup_tpu.cli import parse_eval
    from raft_ncup_tpu.evaluation import (
        VALIDATORS,
        create_kitti_submission,
        create_sintel_submission,
    )
    from raft_ncup_tpu.models.raft import RAFT

    args, model_cfg, data_cfg = parse_eval(argv)
    model = RAFT(model_cfg)
    variables = load_variables(model, model_cfg, args.restore_ckpt)

    if args.export_pth:
        # Serialize the loaded checkpoint as a reference-keyed .pth the
        # reference's strict DataParallel eval load consumes directly
        # (reference: evaluate.py:246-257).
        from raft_ncup_tpu.utils.torch_export import save_torch_checkpoint

        save_torch_checkpoint(args.export_pth, variables)
        print(f"exported reference-keyed checkpoint to {args.export_pth}")
        return

    # --mesh DATA,SPATIAL is the first-class surface (docs/SHARDING.md);
    # --spatial_parallel N stays as reference-era shorthand for 1,N.
    from raft_ncup_tpu.cli import mesh_from_args

    mesh = mesh_from_args(args)
    if mesh is None and args.spatial_parallel > 1:
        from raft_ncup_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(data=1, spatial=args.spatial_parallel)

    iters_kw = {"iters": args.iters} if args.iters is not None else {}
    val_kw = dict(iters_kw)
    if getattr(args, "batch_size", None):
        val_kw["batch_size"] = args.batch_size
    if args.submission:
        if args.dataset == "sintel":
            kwargs = dict(iters_kw)
            if args.output_path:
                kwargs["output_path"] = args.output_path
            create_sintel_submission(
                model, variables, data_cfg,
                warm_start=args.warm_start, write_png=args.write_png,
                mesh=mesh, **kwargs,
            )
        elif args.dataset == "kitti":
            kwargs = dict(iters_kw)
            if args.output_path:
                kwargs["output_path"] = args.output_path
            create_kitti_submission(
                model, variables, data_cfg, write_png=args.write_png,
                mesh=mesh, **kwargs,
            )
        else:
            raise SystemExit("--submission supports sintel/kitti only")
        return

    results = VALIDATORS[args.dataset](
        model, variables, data_cfg, mesh=mesh, **val_kw
    )
    print(results)


if __name__ == "__main__":
    main(sys.argv[1:])
